"""Query executor: PQL AST → compiled device programs over sharded
fragments.

Reference: executor.go (executor.Execute, executeCall, executeBitmapCall,
executeCount, executeTopN, executeSum/Min/Max, executeGroupBy,
executeRows, executeSet/Clear…, mapReduce, mapperLocal/mapperRemote).
Redesigned for TPU:

- every read query executes as ONE jitted program over stacked
  ``uint32[R, S, W]`` field arrays (row-major; see executor/compile.py) — the
  reference's per-shard goroutine fan-out and HTTP reduce collapse into a
  single XLA dispatch with on-device reductions;
- aggregates (Count/Sum/Min/Max/TopN) reduce on device; only scalars (or
  a [rows] count vector for TopN) cross back to the host;
- TopN is EXACT in one pass (per-row masked popcount + sort) instead of
  the reference's approximate cache-fed phase 1; the two-phase recount
  survives only for the ids= form;
- the cluster layer (pilosa_tpu.parallel) fans out non-local shards and
  reduces typed partials; this executor always runs the local portion.
"""

from __future__ import annotations

import os
import threading
import time
from datetime import datetime
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from pilosa_tpu import ops
from pilosa_tpu.core import (
    BSI_OFFSET,
    FIELD_INT,
    VIEW_BSI,
    VIEW_STANDARD,
    Field,
    Holder,
    Index,
)
from pilosa_tpu.executor.compile import (
    PlanError,
    QueryCompiler,
    StackOverBudget,
    _stack_budget,
)
from pilosa_tpu.executor.hostpath import HostPlanError
from pilosa_tpu.executor.router import QueryRouter, estimate_words
from pilosa_tpu.executor.row import RowResult
from pilosa_tpu.pql import Call, coerce_timestamp, parse
from pilosa_tpu.roaring import unpack_words
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.utils import tracing
from pilosa_tpu.utils.tracing import GLOBAL_TRACER

def apply_options(idx: "Index", call: "Call", res: Any) -> Any:
    """Apply an Options() wrapper's result-shaping args (reference:
    QueryRequest ColumnAttrs/ExcludeColumns/ExcludeRowAttrs). Shared by
    the local executor and the cluster coordinator (which re-applies
    after merging per-node partials)."""
    if isinstance(res, RowResult):
        if call.arg("excludeColumns"):
            res.exclude_columns = True
        if call.arg("excludeRowAttrs"):
            res.exclude_row_attrs = True
        if call.arg("columnAttrs"):
            sets = []
            for col in res.columns().tolist():
                attrs = idx.column_attrs.attrs(int(col))
                if attrs:
                    entry: dict = {"id": int(col), "attrs": attrs}
                    if idx.options.keys:
                        key = idx.column_keys.translate_id(int(col))
                        if key is not None:
                            entry["key"] = key
                    sets.append(entry)
            res.column_attr_sets = sets
    return res


BITMAP_CALLS = {
    "Row",
    "Range",
    "Union",
    "Intersect",
    "Difference",
    "Xor",
    "Not",
    "All",
    "Shift",
}
WRITE_CALLS = {
    "Set",
    "Clear",
    "ClearRow",
    "Store",
    "SetRowAttrs",
    "SetColumnAttrs",
}


def unwrap_options(call: Call) -> Call:
    """Innermost call of an Options() wrapper chain — THE write/read
    classification rule; the cluster router and the max_writes limit must
    agree on it."""
    while call.name == "Options" and len(call.children) == 1:
        call = call.children[0]
    return call


class ExecutionError(ValueError):
    pass


def finalize(results: list) -> list:
    """Dispatched results → client-facing values (resolved pendings
    replaced by their finished values). Shared by Executor.execute and
    the wave scheduler's per-query completion."""
    return [r.value if isinstance(r, _Pending) else r for r in results]


class _Pending:
    """Deferred on-device aggregate values. execute() resolves EVERY
    pending result in one readback wave after all calls have dispatched:
    the device arrays are raveled to int64, concatenated into one buffer,
    and fetched with a single device→host transfer — an N-aggregate
    request pays one transport RTT, not N (VERDICT r3 weak #3: with only
    Count pipelined, sync TopN ran at ~1/RTT and GroupBy below the CPU
    baseline). The same mechanism settles CROSS-QUERY waves: the
    dispatch scheduler (executor/scheduler.py) concatenates pendings
    from many concurrent requests into one transfer. `finish` turns the
    fetched host arrays (original shapes) into the final result;
    ``fetched`` holds them between the transfer (scheduler.fetch_wave)
    and the per-query resolve so one query's finish() failure cannot
    strand its wave-mates."""

    __slots__ = ("arrays", "finish", "value", "fetched", "route", "audit")

    def __init__(
        self,
        arrays: list,
        finish: "Callable[[list], Any]",
        route: str = "device",
    ) -> None:
        self.arrays = list(arrays)
        self.finish = finish
        self.value = None
        self.fetched: list | None = None
        # which engine produced the arrays ("device" | "mesh") — the
        # readback wave attributes its measured latency to the matching
        # router EWMA so the two paths calibrate independently
        self.route = route
        # settle-time router-audit record ({route, estimates,
        # dispatch_s}), completed when the readback wave lands and the
        # call's full measured cost is known; popped on first use so a
        # per-query fallback fetch after a poisoned joint readback
        # cannot double-score the call
        self.audit: dict | None = None

    def resolve_now(self) -> Any:
        self.value = self.finish([np.asarray(a) for a in self.arrays])
        return self.value

    def resolve_fetched(self) -> Any:
        """Finish from host arrays a prior fetch_wave stored — no device
        access; safe to call per query with per-query error isolation."""
        assert self.fetched is not None, "resolve_fetched before fetch"
        self.value = self.finish(self.fetched)
        return self.value


@jax.jit
def _gb_counts(masks, matrix, rows):
    """GroupBy level counts: [G,S,W] masks × K candidate rows (gathered
    from the [R,S,W] row-major stack) → int64[G,K] in one dispatch
    (lax.map bounds transient memory to one row batch)."""
    gathered = jnp.take(matrix, rows, axis=0, mode="fill", fill_value=0)
    # popcount_rows accumulates the trailing axis in i32 (≤ 2^20 bits per
    # row); i64 only for the [G,S] partials — an i64 [G,S,W] intermediate
    # would relayout-copy the stack (see ops.bitwise.popcount)
    per_row = lambda rm: jnp.sum(
        ops.popcount_rows(masks & rm[None]).astype(jnp.int64), axis=1
    )
    return jax.lax.map(per_row, gathered).T


@jax.jit
def _gb_masks(masks, matrix, g_idx, row_sel):
    """Materialize surviving groups' masks: gather parent masks and
    candidate rows (axis 0 of the row-major stack), AND them — one
    dispatch per level."""
    sel = jnp.take(masks, g_idx, axis=0)
    rows = jnp.take(matrix, row_sel, axis=0, mode="fill", fill_value=0)
    return sel & rows


class SumCount(dict):
    """Sum/Min/Max result: {"value": v, "count": n} (reference: ValCount)."""

    def __init__(self, value: int, count: int):
        super().__init__(value=int(value), count=int(count))


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def _pad_row_ids(rows: list[int], k_pad: int) -> np.ndarray:
    """Row ids padded to k_pad with -1: jnp.take(mode="fill") turns the
    padding into all-zero rows, so padded slots count 0 and prune."""
    arr = np.full(k_pad, -1, dtype=np.int32)
    arr[: len(rows)] = rows
    return arr


class Executor:
    # device-memory cap for GroupBy's [G, S, W] group-mask tensor; levels
    # surviving more groups than fit are processed in chunks (see
    # _execute_group_by). None ⇒ resolved lazily from device HBM in
    # _gb_budget(); tests pin an int (class or instance) to force paths.
    GROUPBY_MASK_BUDGET = None

    def _gb_budget(self) -> int:
        """GroupBy transient-mask budget: a pinned GROUPBY_MASK_BUDGET
        wins; else PILOSA_TPU_GROUPBY_BUDGET env; else 1/8 of the stack
        budget (~70% of HBM), floored at 256 MiB. Sized so a realistic
        two-level GroupBy folds through the FUSED one-readback path on a
        real chip instead of paying one sync RTT per level — round 3
        measured the chunked path BELOW the CPU baseline through the
        tunnel. Lazy: resolving device memory must never happen at
        construction (backend init)."""
        if self.GROUPBY_MASK_BUDGET is not None:
            return self.GROUPBY_MASK_BUDGET
        env = os.environ.get("PILOSA_TPU_GROUPBY_BUDGET")
        if env:
            return int(env)
        return max(256 << 20, _stack_budget() // 8)

    def __init__(
        self,
        holder: Holder,
        mesh_ctx=None,
        stats=None,
        route_mode: str | None = None,
        router: QueryRouter | None = None,
    ):
        self.holder = holder
        self.stats = stats  # optional StatsClient for per-call histograms
        self.compiler = QueryCompiler(mesh_ctx, stats=stats)
        # per-call host/device routing (executor/router.py). Passing an
        # existing router preserves its calibration across executor
        # rebuilds (the server's mesh re-attach swaps the Executor but
        # the measured crossover must not reset to seeds).
        self.router = (
            router
            if router is not None
            else QueryRouter(mode=route_mode, stats=stats)
        )
        # the router's mesh path exists only while a multi-device mesh
        # is attached; a rebuild WITHOUT one (failed attach, CPU pin)
        # must also reset it or a persistent router would keep routing
        # to an engine the new executor doesn't have
        self.router.mesh_devices = (
            self.compiler.mesh_engine.n_devices
            if self.compiler.mesh_engine is not None
            else 1
        )
        # per-query-string route cache: the expensive half of routing is
        # building the decision INPUTS (structural repr for the memo
        # key, the work estimate's tree walk, the residency cold-row
        # probe) — all re-derived per request even though decisions are
        # stable. Entries revalidate every _ROUTE_CACHE_HITS hits, so
        # calibration drift, data growth, and tier promotion re-route
        # within a bounded number of queries (see _routes_for for why
        # the drift generation is deliberately NOT part of the key).
        from collections import OrderedDict

        self._route_cache: "OrderedDict[tuple, list]" = OrderedDict()
        # OrderedDict's relink on move_to_end/popitem is not safe under
        # concurrent HTTP worker threads; the critical section is a few
        # dict ops, so one uncontended lock costs ~nothing per query
        self._route_cache_lock = threading.Lock()

    _ROUTE_CACHE_HITS = 64
    _ROUTE_CACHE_MAX = 512

    def _routes_for(
        self,
        idx: Index,
        index_name: str,
        query,
        calls: "list[Call]",
        shards: list[int] | None,
    ) -> "list[tuple[str | None, int, bool, int]]":
        """One route spec — ``(route, work, mesh_ok, cold_words)``, the
        _route tuple — per call, via the revalidating cache when the
        query arrived as a raw string (the serving hot path)."""
        if not isinstance(query, str):
            return [self._route(idx, c, shards) for c in calls]
        # deliberately NOT keyed on the router's drift generation: the
        # bounded hit count IS the staleness limit — calibration drift
        # re-routes within _ROUTE_CACHE_HITS queries, while keying on
        # the generation would invalidate the whole cache on every EWMA
        # wiggle and hand the hot path the full probe cost back
        key = (
            index_name,
            query,
            tuple(shards) if shards is not None else None,
            self.router.mode,
        )
        with self._route_cache_lock:
            ent = self._route_cache.get(key)
            if ent is not None and ent[0] > 0 and len(ent[1]) == len(calls):
                ent[0] -= 1
                self._route_cache.move_to_end(key)
                return ent[1]
        routes = [self._route(idx, c, shards) for c in calls]
        with self._route_cache_lock:
            self._route_cache[key] = [self._ROUTE_CACHE_HITS, routes]
            self._route_cache.move_to_end(key)
            while len(self._route_cache) > self._ROUTE_CACHE_MAX:
                self._route_cache.popitem(last=False)
        return routes

    # ------------------------------------------------------------ entry
    def execute(
        self,
        index_name: str,
        query: str | list[Call],
        shards: list[int] | None = None,
        routes: "list[tuple[str | None, int, bool, int]] | None" = None,
    ) -> list[Any]:
        results = self.dispatch(index_name, query, shards, routes=routes)
        pending = [r for r in results if isinstance(r, _Pending)]
        if pending:
            elapsed = self.settle(pending)
            prof = tracing.current_profile()
            if prof is not None:
                # the one device→host sync the whole request pays; on a
                # tunneled accelerator this line IS the latency story
                prof.add_call("_readback", elapsed, None)
        return finalize(results)

    def dispatch(
        self,
        index_name: str,
        query: str | list[Call],
        shards: list[int] | None = None,
        routes: "list[tuple[str | None, int, bool, int]] | None" = None,
    ) -> list[Any]:
        """Issue every call WITHOUT the readback wave — aggregates come
        back as unresolved ``_Pending``s. This is the enqueue half the
        cross-query scheduler shares: a wave dispatches many queries
        through here, then settles ALL their pendings in one transfer
        (settle / scheduler.fetch_wave). Aggregates dispatch ASYNC
        (device arrays, not yet synced) in program order, so an
        aggregate preceding a write still reads pre-write state —
        exactly the sequential semantics. Per-call dispatch is spanned +
        histogram-timed (the readback wave is timed separately:
        pipelining means a call's device time is not attributable to its
        own dispatch).  ``routes`` optionally carries per-call
        ``(route, work, mesh_ok, cold_words)`` specs a caller (the wave
        scheduler's batchability check) already computed, so the hot
        path doesn't pay the work estimation twice; the trailing
        elements feed the settle-time router audit."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecutionError(f"index {index_name!r} not found")
        calls = parse(query) if isinstance(query, str) else query
        prof = tracing.current_profile()
        prof_shards: list[int] | None = None
        if routes is None:
            routes = self._routes_for(idx, index_name, query, calls, shards)
        results = []
        for i, c in enumerate(calls):
            t0 = time.perf_counter()
            route, work = routes[i][0], routes[i][1]
            with GLOBAL_TRACER.span(f"executor.{c.name}", index=index_name):
                results.append(
                    self._execute_call(idx, c, shards, lazy=True, route=route)
                )
            elapsed = time.perf_counter() - t0
            if route in ("host", "device", "mesh"):
                self.router.record(route)
                if work > 0:
                    # feed the calibration: host samples refine host
                    # throughput/overhead, device/mesh samples their
                    # respective dispatch costs
                    self.router.observe(route, work, elapsed)
                if work > 0 and self.router.audit.enabled:
                    # settle-time decision audit: snapshot every
                    # candidate's estimate NOW (the decision's inputs);
                    # host calls score immediately — their elapsed IS
                    # the full cost — while device/mesh pendings carry
                    # the record to the readback wave, where the
                    # measured cost completes (Executor.fetch)
                    spec = routes[i]
                    est = self._candidate_costs(
                        route,
                        work,
                        spec[2] if len(spec) > 2 else False,
                        spec[3] if len(spec) > 3 else 0,
                    )
                    res = results[-1]
                    if isinstance(res, _Pending):
                        res.audit = {
                            "route": route,
                            "estimates": est,
                            "dispatch_s": elapsed,
                        }
                    else:
                        self.router.audit.record(route, est, elapsed)
                if self.stats is not None:
                    self.stats.count("queries_routed", tags={"path": route})
                if route == "mesh" and prof is not None:
                    # ?profile=true names the mesh route per call (the
                    # entry's route tag) AND the mesh geometry once
                    prof.mesh = self.compiler.mesh_snapshot()
            if self.stats is not None:
                self.stats.timing(
                    "executor_call_seconds", elapsed, tags={"call": c.name}
                )
            if prof is not None:
                if prof_shards is None:
                    prof_shards = self._shards(idx, shards)
                prof.add_call(c.name, elapsed, prof_shards, route=route)
        if prof is not None and self.compiler.stacks._tiered:
            # residency block in ?profile=true: which container tiers
            # served this query's over-budget fields and the promotion /
            # demotion counters at the time it ran
            prof.residency = self.compiler.stacks.residency_snapshot()
        return results

    def fetch(self, pending: "list[_Pending]") -> float:
        """One device→host transfer for every pending's arrays (the
        settlement layer lives in executor/scheduler.py — fetch_wave is
        the ONLY sanctioned readback site, per the readback analyzer
        rule). Leaves each pending's host arrays on ``p.fetched``;
        callers resolve per query so one finish() failure can't poison
        wave-mates. Records the readback histogram + router calibration."""
        if not pending:
            return 0.0
        from pilosa_tpu.executor.scheduler import fetch_wave

        t0 = time.perf_counter()
        fetch_wave(pending)
        elapsed = time.perf_counter() - t0
        # attribute the wave's measured latency to every path that rode
        # it — mesh and device pendings calibrate separate EWMAs, and a
        # shared wave's cost is what each path's queries actually paid
        for path in {p.route for p in pending}:
            self.router.observe_readback(elapsed, path=path)
        # complete the settle-time audit records: each pending call's
        # measured cost is its own dispatch plus its share of the one
        # transfer the wave paid (mirroring the cost model's amortized
        # readback term). Records pop on first use so the per-query
        # fallback fetch after a poisoned joint readback can't
        # double-score a call.
        share = elapsed / len(pending)
        for p in pending:
            rec = p.audit
            if rec is not None:
                p.audit = None
                self.router.audit.record(
                    rec["route"], rec["estimates"], rec["dispatch_s"] + share
                )
        if self.stats is not None:
            self.stats.timing("executor_readback_seconds", elapsed)
        return elapsed

    def settle(self, pending: "list[_Pending]") -> float:
        """Fetch + resolve a pending set (one query's, or a whole wave's
        when the caller doesn't need per-query error isolation)."""
        elapsed = self.fetch(pending)
        for p in pending:
            p.resolve_fetched()
        return elapsed

    def _shards(self, idx: Index, shards: list[int] | None) -> list[int]:
        if shards is not None:
            return sorted(shards)
        avail = idx.available_shards()
        return sorted(avail) if avail else [0]

    # ------------------------------------------------------------ routing
    def _route(self, idx: Index, call: Call, shards: list[int] | None):
        """(route, estimated_work_words, mesh_ok, cold_upload_words)
        for one top-level call.  Writes route None (no engine choice to
        make); Rows is metadata-only and always serves host-side.
        Reads go through the cost router — decision memoized per plan
        key (executor/router.py) — which picks among host, the
        single-program device path, and (when a multi-device
        MeshContext is attached and the call tree compiles to mesh
        programs) the explicit-SPMD mesh path.  The trailing elements
        carry the decision INPUTS forward so the settle-time audit and
        EXPLAIN can rebuild every candidate's cost without re-walking
        the tree."""
        c, sh = call, shards
        while c.name == "Options" and len(c.children) == 1:
            sh = c.arg("shards", sh)
            c = c.children[0]
        if c.name in WRITE_CALLS:
            return None, 0, False, 0
        if c.name == "Rows":
            return "host", 0, False, 0
        n = len(sh) if sh is not None else max(1, len(idx.available_shards()))
        work = estimate_words(idx, c, n)
        if self.router.mode in ("host", "device"):
            # pinned modes never consult mesh eligibility or the cold-row
            # cost term — skip the residency walk on their hot path
            tiered, cold_words = False, 0
        else:
            tiered, cold_words = self._residency_info(idx, c, sh)
        # tiered container stores hold payloads in GLOBAL position space,
        # which a shard_map program's per-device block cannot decode —
        # tiered-touched trees stay on the single-program device path
        # (the stores themselves are mesh-placed, so SPMD reads of the
        # decoded planes keep working)
        mesh_ok = self._mesh_ok(c, n) and not tiered
        if self.router.mode != "auto":
            mode = self.router.mode
            if mode == "mesh" and not mesh_ok:
                # fallback-annotated call type (parallel.mesh) or a
                # replicate-only shape: the single-program device path
                # serves it (still SPMD via the stacks' NamedSharding)
                mode = "device"
                if self.compiler.mesh_engine is not None:
                    self.compiler.mesh_engine.note_fallback()
            return mode, work, mesh_ok, cold_words
        return (
            self.router.decide(
                (idx.name, n, repr(c)),
                work,
                mesh_ok=mesh_ok,
                device_extra_words=cold_words,
            ),
            work,
            mesh_ok,
            cold_words,
        )

    def _candidate_costs(
        self, route: str, work: int, mesh_ok: bool, cold_words: int
    ) -> dict:
        """Modeled cost in seconds for every candidate path of one call
        — the decision's inputs, snapshotted for the settle-time audit
        and the EXPLAIN cost table.  Mesh appears only when it was a
        real candidate (eligible and multi-device) or was actually
        chosen (pinned mode)."""
        r = self.router
        extra_s = cold_words / r._host_wps() if cold_words else 0.0
        costs = {
            "host": r.host_cost(work),
            "device": r.device_cost(work) + extra_s,
        }
        if (mesh_ok and r.mesh_devices > 1) or route == "mesh":
            costs["mesh"] = r.mesh_cost(work) + extra_s
        return costs

    def _residency_info(
        self, idx: Index, call: Call, shards: list[int] | None,
        detail: list | None = None,
    ) -> tuple[bool, int]:
        """(touches_tiered_field, cold_upload_words) for one call tree.

        Every COLD row of a tiered (over-budget) field costs the device
        path roughly one host-packed [S, W] plane upload — the router
        charges that against the device route so a one-shot scan of a
        cold working set serves host-side, while a re-touched (promoted)
        set routes back to the device.  Promotion itself is driven by
        the touch counts the tiered layer keeps; this probe never
        mutates them."""
        stacks = self.compiler.stacks
        if stacks.residency_mode() == "slots":
            return False, 0
        shard_list = self._shards(idx, shards)
        unit = len(shard_list) * WORDS_PER_SHARD
        over_budget: dict[tuple, bool] = {}

        def over(field: Field, view_name: str) -> bool:
            k = (field.name, view_name)
            got = over_budget.get(k)
            if got is None:
                got = stacks.is_over_budget(idx, field, view_name, shard_list)
                over_budget[k] = got
            return got

        tiered = False
        cold = 0

        def leaf(field: Field, view_name: str, row_id) -> None:
            nonlocal tiered, cold
            if not over(field, view_name):
                if detail is not None:
                    detail.append(
                        {
                            "field": field.name,
                            "view": view_name,
                            "row": row_id,
                            "class": "in-budget",
                        }
                    )
                return
            tiered = True
            resident = stacks.tiered_resident(
                idx, field, view_name, shard_list, row_id
            )
            if not resident:
                cold += unit
            if detail is not None:
                detail.append(
                    {
                        "field": field.name,
                        "view": view_name,
                        "row": row_id,
                        "class": "resident" if resident else "cold",
                    }
                )

        def walk(c: Call) -> None:
            nonlocal tiered, cold
            if c.name in ("Row", "Range"):
                cond = c.condition()
                if cond is not None:
                    f = idx.field(cond[0])
                    if f is not None and over(f, VIEW_BSI):
                        tiered = True
                        need = BSI_OFFSET + f.bit_depth
                        cold_slices = 0
                        for d in range(need):
                            if not stacks.tiered_resident(
                                idx, f, VIEW_BSI, shard_list, d
                            ):
                                cold += unit
                                cold_slices += 1
                        if detail is not None:
                            detail.append(
                                {
                                    "field": f.name,
                                    "view": VIEW_BSI,
                                    "slices": need,
                                    "coldSlices": cold_slices,
                                    "class": (
                                        "cold" if cold_slices else "resident"
                                    ),
                                }
                            )
                    return
                fa = c.field_arg()
                if fa is not None:
                    f = idx.field(fa[0])
                    if f is not None:
                        row = fa[1]
                        if isinstance(row, bool):
                            row = int(row)
                        if isinstance(row, int):
                            leaf(f, VIEW_STANDARD, row)
                return
            if c.name in ("Sum", "Min", "Max"):
                # the aggregate's own BSI block is read too — an
                # over-budget one serves via tiered slice containers,
                # which the mesh programs cannot consume
                fname = c.arg("field") or (
                    c.pos_args[0] if c.pos_args else None
                )
                f = idx.field(fname) if isinstance(fname, str) else None
                if f is not None and f.options.field_type == FIELD_INT and over(
                    f, VIEW_BSI
                ):
                    tiered = True
                    need = BSI_OFFSET + f.bit_depth
                    cold_slices = 0
                    for d in range(need):
                        if not stacks.tiered_resident(
                            idx, f, VIEW_BSI, shard_list, d
                        ):
                            cold += unit
                            cold_slices += 1
                    if detail is not None:
                        detail.append(
                            {
                                "field": f.name,
                                "view": VIEW_BSI,
                                "slices": need,
                                "coldSlices": cold_slices,
                                "class": (
                                    "cold" if cold_slices else "resident"
                                ),
                            }
                        )
            for ch in c.children:
                walk(ch)
            filt = c.arg("filter")
            if isinstance(filt, Call):
                walk(filt)
            agg = c.arg("aggregate")
            if isinstance(agg, Call):
                walk(agg)

        walk(call)
        return tiered, cold

    def _mesh_ok(self, call: Call, n_shards: int) -> bool:
        """Can this call run as explicit mesh programs right now — a mesh
        engine is attached, the shard/word shapes actually shard onto it,
        and every node of the tree has a mesh program (no fallback
        annotations)?  Deferred import: executor modules must not pull
        parallel/ in at import time."""
        if self.compiler.mesh_engine is None:
            return False
        if self.compiler.mesh_mode(n_shards) is None:
            return False
        from pilosa_tpu.parallel.mesh import mesh_supported

        return mesh_supported(call)

    def route_for(
        self,
        index_name: str,
        query: "str | Call | list[Call]",
        shards: list[int] | None = None,
    ) -> str:
        """The route a query's first call would take right now — the
        reporting hook bench.py/bench_all.py stamp into their rows."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecutionError(f"index {index_name!r} not found")
        calls = parse(query) if isinstance(query, str) else query
        first = calls[0] if isinstance(calls, list) else calls
        route = self._route(idx, first, shards)[0]
        return route or "write"

    def explain_call(
        self, idx: Index, call: Call, shards: list[int] | None
    ) -> dict:
        """The EXPLAIN plan for one top-level call — every decision the
        serving path would make, WITHOUT executing anything: the router
        cost table per candidate path, the residency classification of
        every touched row range, the mesh supportability verdict, and
        the work estimate behind them all.  Metadata-only by
        construction (the same fragment/schema probes the router's hot
        path uses); nothing here touches JAX."""
        c, sh = call, shards
        while c.name == "Options" and len(c.children) == 1:
            sh = c.arg("shards", sh)
            c = c.children[0]
        if c.name in WRITE_CALLS:
            return {"call": c.name, "route": "write"}
        if c.name == "Rows":
            return {
                "call": c.name,
                "route": "host",
                "note": "metadata-only call; always served host-side",
            }
        n = len(sh) if sh is not None else max(1, len(idx.available_shards()))
        work = estimate_words(idx, c, n)
        res_detail: list = []
        tiered, cold_words = self._residency_info(idx, c, sh, detail=res_detail)
        # mesh supportability, verdict + reason (docs/spmd.md)
        mesh_attached = self.compiler.mesh_engine is not None
        geometry_ok = mesh_attached and self.compiler.mesh_mode(n) is not None
        programs_ok = False
        if geometry_ok:
            from pilosa_tpu.parallel.mesh import mesh_supported

            programs_ok = mesh_supported(c)
        multi_device = self.router.mesh_devices > 1
        mesh_ok = geometry_ok and programs_ok and not tiered and multi_device
        if not mesh_attached:
            mesh_reason = "no mesh engine attached"
        elif not multi_device:
            mesh_reason = "single device — mesh path disabled"
        elif not geometry_ok:
            mesh_reason = "shard/word geometry does not place onto the mesh"
        elif not programs_ok:
            mesh_reason = "call tree contains mesh-fallback calls"
        elif tiered:
            mesh_reason = (
                "tiered residency pins to the single-program device path"
            )
        else:
            mesh_reason = "supported"
        # the route the router takes RIGHT NOW — same decision inputs
        # and memo path as _route, but WITHOUT re-running the residency
        # and mesh-supportability walks this function already did (and
        # without _route's fallback-counter side effect, which counts
        # real serving fallbacks only)
        if self.router.mode != "auto":
            route = self.router.mode
            if route == "mesh" and not mesh_ok:
                route = "device"
        else:
            route = self.router.decide(
                (idx.name, n, repr(c)),
                work,
                mesh_ok=mesh_ok,
                device_extra_words=cold_words,
            )
        costs = self._candidate_costs(route, work, mesh_ok, cold_words)
        return {
            "call": c.name,
            "route": route,
            "routeMode": self.router.mode,
            "estimatedWorkWords": work,
            "crossoverWords": self.router.crossover_words(),
            "candidates": {
                path: {"estimatedSeconds": s, "chosen": path == route}
                for path, s in sorted(costs.items())
            },
            "residency": {
                "mode": self.compiler.stacks.residency_mode(),
                "tiered": tiered,
                "coldUploadWords": cold_words,
                "rowRanges": res_detail,
            },
            "mesh": {
                "supported": mesh_ok,
                "reason": mesh_reason,
                "meshDevices": self.router.mesh_devices,
            },
        }

    def _execute_call(
        self,
        idx: Index,
        call: Call,
        shards: list[int] | None,
        lazy: bool = False,
        route: str | None = "device",
    ) -> Any:
        name = call.name
        if name == "Options":
            if len(call.children) != 1:
                raise ExecutionError("Options() takes exactly one call")
            opt_shards = call.arg("shards", shards)
            res = self._execute_call(
                idx, call.children[0], opt_shards, lazy=lazy, route=route
            )
            if isinstance(res, _Pending):
                # shape at resolve time so Options() args still apply
                inner = res.finish
                res.finish = lambda a: apply_options(idx, call, inner(a))
                return res
            return apply_options(idx, call, res)
        if name in WRITE_CALLS:
            return self._execute_write(idx, call)
        shard_list = self._shards(idx, shards)
        host = route == "host"
        # trust-but-verify the mesh route: the decision was made with
        # _mesh_ok, but a direct caller may pass route="mesh" blindly
        mesh = route == "mesh" and self.compiler.mesh_engine is not None
        try:
            if name in BITMAP_CALLS:
                if host:
                    # np.array: the host engine may hand back views of
                    # live stack memory; the result a client keeps must
                    # not alias storage a later write scatters into
                    words = np.array(
                        self.compiler.host.bitmap_words(idx, call, shard_list)
                    )
                elif mesh:
                    words = self.compiler.mesh_bitmap_words(
                        idx, call, shard_list
                    )
                else:
                    words = self._bitmap_words(idx, call, shard_list)
                res = RowResult(
                    {s: words[i] for i, s in enumerate(shard_list)}
                )
                self._attach_keys(idx, res)
                self._attach_row_attrs(idx, call, res)
                return res
            if name == "Count":
                if len(call.children) != 1:
                    raise ExecutionError("Count() takes exactly one call")
                if host:
                    # concrete scalar, no _Pending, no readback wave
                    return self.compiler.host.count(
                        idx, call.children[0], shard_list
                    )
                if mesh:
                    pend = _Pending(
                        [
                            self.compiler.mesh_count_async(
                                idx, call.children[0], shard_list
                            )
                        ],
                        lambda a: int(a[0]),
                        route="mesh",
                    )
                else:
                    pend = _Pending(
                        [
                            self.compiler.count_async(
                                idx, call.children[0], shard_list
                            )
                        ],
                        lambda a: int(a[0]),
                    )
                return pend if lazy else pend.resolve_now()
            if name == "Sum":
                return self._execute_sum(
                    idx, call, shard_list, lazy=lazy, host=host, mesh=mesh
                )
            if name in ("Min", "Max"):
                return self._execute_min_max(
                    idx, call, shard_list, name == "Max", lazy=lazy,
                    host=host, mesh=mesh,
                )
            if name == "TopN":
                return self._execute_topn(
                    idx, call, shard_list, lazy=lazy, host=host, mesh=mesh
                )
            if name == "Rows":
                return self._execute_rows(idx, call, shard_list)
            if name == "GroupBy":
                return self._execute_group_by(
                    idx, call, shard_list, lazy=lazy, host=host, mesh=mesh
                )
            if name == "IncludesColumn":
                return self._execute_includes_column(
                    idx, call, shard_list, host=host
                )
        except (PlanError, StackOverBudget, HostPlanError) as e:
            raise ExecutionError(str(e)) from e
        raise ExecutionError(f"unknown call {name!r}")

    # ----------------------------------------------------------- helpers
    def _bitmap_words(self, idx: Index, call: Call, shards: list[int]) -> np.ndarray:
        try:
            return self.compiler.bitmap_words(idx, call, shards)
        except PlanError as e:
            raise ExecutionError(str(e)) from e

    def _field(self, idx: Index, name: str) -> Field:
        f = idx.field(name)
        if f is None:
            raise ExecutionError(f"field {name!r} not found")
        return f

    def _row_id(self, field: Field, row: Any, create: bool = False) -> int | None:
        if isinstance(row, bool):
            return int(row)
        if isinstance(row, int):
            return row
        if isinstance(row, str):
            if not field.options.keys:
                raise ExecutionError(
                    f"field {field.name!r} does not use string keys"
                )
            return field.row_keys.translate_key(row, create=create)
        raise ExecutionError(f"bad row value {row!r}")

    def _col_id(self, idx: Index, col: Any, create: bool = False) -> int | None:
        if isinstance(col, int) and not isinstance(col, bool):
            return col
        if isinstance(col, str):
            if not idx.options.keys:
                raise ExecutionError(f"index {idx.name!r} does not use string keys")
            return idx.column_keys.translate_key(col, create=create)
        raise ExecutionError(f"bad column value {col!r}")

    def _attach_row_attrs(self, idx: Index, call: Call, res: RowResult) -> None:
        """Direct Row(field=row) results carry the row's attributes
        (reference: QueryResult Row.Attrs)."""
        if call.name != "Row" or call.condition() is not None:
            return
        fa = call.field_arg()
        if fa is None:
            return
        field = idx.field(fa[0])
        if field is None:
            return
        row_id = fa[1]
        if isinstance(row_id, str):
            if not field.options.keys:
                return
            row_id = field.row_keys.translate_key(row_id, create=False)
            if row_id is None:
                return
        if isinstance(row_id, bool):
            row_id = int(row_id)
        if isinstance(row_id, int):
            res.attrs = field.row_attrs.attrs(row_id)

    def _attach_keys(self, idx: Index, res: RowResult) -> None:
        if idx.options.keys:
            cols = res.columns().tolist()
            res.keys = [idx.column_keys.translate_id(c) or str(c) for c in cols]

    def _call_field_name(self, call: Call) -> str:
        fname = call.arg("field")
        if fname is None and call.pos_args:
            fname = call.pos_args[0]
        if fname is None:
            raise ExecutionError(f"{call.name}() needs a field argument")
        return fname

    def _agg_field(self, idx: Index, call: Call) -> Field:
        field = self._field(idx, self._call_field_name(call))
        if field.options.field_type != FIELD_INT:
            raise ExecutionError(f"field {field.name!r} is not an int field")
        return field

    def _filter_device(self, idx: Index, call: Call, shards: list[int]):
        """Child-call filter as a device array [S, W]; all-ones when
        absent (cached per shard count)."""
        if call.children:
            try:
                return self.compiler.bitmap_device(idx, call.children[0], shards)
            except PlanError as e:
                raise ExecutionError(str(e)) from e
        return self.compiler.ones(len(shards))

    def _filter_plan(
        self,
        idx: Index,
        call: Call,
        shards: list[int],
        mesh_mode: str | None = None,
    ):
        """Plan a filter child for IN-PROGRAM fusion: (run, arrays,
        scalars, skey), or None when the call has no filter. The filter
        expression computes inside the aggregate's own XLA program, so
        the [S, W] filter never materializes to HBM between two
        dispatches (VERDICT r3 weak #2: the separate filter program was
        part of the executor-vs-raw-kernel bandwidth gap).  With
        ``mesh_mode`` the closure traces against the mesh's per-device
        block shape so it can fuse into a shard_map program."""
        if not call.children:
            return None
        try:
            if mesh_mode is not None:
                planner, run, skey = self.compiler.mesh_plan(
                    idx, call.children[0], shards, mesh_mode
                )
            else:
                planner, run, skey = self.compiler._plan(
                    idx, call.children[0], shards
                )
        except PlanError as e:
            raise ExecutionError(str(e)) from e
        arrays = planner.materialize()
        scalars = self.compiler.device_scalars(planner.scalar_values())
        return run, arrays, scalars, skey

    def _bsi_stacked(self, idx: Index, field: Field, shards: list[int]):
        """uint32[D, S, W] bit-slice block for an int field (device,
        row-major like every stack). Over-budget BSI stacks assemble
        from tiered compressed slice rows in tiered residency mode
        (docs/device-residency.md); the legacy slots mode surfaces the
        budget error clearly as before."""
        try:
            m, _rows = self.compiler.stacks.matrix(idx, field, VIEW_BSI, shards)
        except StackOverBudget as e:
            if self.compiler.stacks.residency_mode() == "slots":
                raise ExecutionError(str(e)) from e
            try:
                return self.compiler.tiered_bsi_block(idx, field, shards)
            except StackOverBudget as e2:
                raise ExecutionError(str(e2)) from e2
        need = BSI_OFFSET + field.bit_depth
        if m.shape[0] < need:
            m = jnp.pad(m, ((0, need - m.shape[0]), (0, 0), (0, 0)))
        return m[:need]

    # ------------------------------------------------------- aggregates
    @staticmethod
    def _sum_fn(s, f):
        """(slices [D,S,W], filt [S,W]) → (pos[D], neg[D], n) — the ONE
        BSI-sum reduction body; Sum jits it directly and GroupBy's
        aggregate wraps it in a group vmap so the two stay in sync.
        vmap over the shard axis (axis 1 of the row-major block)."""
        return tuple(
            x.astype(jnp.int64).sum(axis=0)
            for x in jax.vmap(ops.bsi.sum_counts, in_axes=(1, 0))(s, f)
        )

    def _sum_program(self, field: Field, n_shards: int):
        return self.compiler.wrapped_program(
            ("sum", n_shards, field.bit_depth), lambda: jax.jit(self._sum_fn)
        )

    def _grouped_sum_program(self, field: Field, n_shards: int):
        """(slices [D,S,W], masks [G,S,W]) → (pos[G,D], neg[G,D], n[G])."""
        return self.compiler.wrapped_program(
            ("gb_sums", n_shards, field.bit_depth),
            lambda: jax.jit(jax.vmap(self._sum_fn, in_axes=(None, 0))),
        )

    def _execute_sum(
        self, idx: Index, call: Call, shards: list[int], lazy: bool = False,
        host: bool = False, mesh: bool = False,
    ):
        field = self._agg_field(idx, call)
        if host:
            value, n = self.compiler.host.sum(idx, field, call, shards)
            return SumCount(value, n)
        slices = self._bsi_stacked(idx, field, shards)
        if mesh:
            mode = self.compiler.mesh_mode(len(shards))
            eng = self.compiler.mesh_engine
            fplan = self._filter_plan(idx, call, shards, mesh_mode=mode)
            if fplan is not None:
                frun, farrays, fscalars, fskey = fplan
                key = ("mesh_sum", len(shards), field.bit_depth, mode, fskey)
                prog = self.compiler.program(
                    key, lambda: eng.sum_tree(self._sum_fn, mode, frun=frun)
                )
                pos, neg, n = self.compiler._mesh_dispatch(
                    "sum", key, prog, slices, farrays, fscalars
                )
            else:
                key = ("mesh_sum", len(shards), field.bit_depth, mode)
                prog = self.compiler.program(
                    key, lambda: eng.sum_tree(self._sum_fn, mode)
                )
                pos, neg, n = self.compiler._mesh_dispatch(
                    "sum", key, prog, slices, self.compiler.ones(len(shards))
                )
        else:
            fplan = self._filter_plan(idx, call, shards)
            if fplan is not None:
                frun, farrays, fscalars, fskey = fplan
                pos, neg, n = self.compiler.run_program(
                    ("sum", len(shards), field.bit_depth, fskey),
                    lambda: jax.jit(
                        lambda s, fa, fs: self._sum_fn(s, frun(fa, fs))
                    ),
                    slices,
                    farrays,
                    fscalars,
                )
            else:
                filt = self.compiler.ones(len(shards))
                pos, neg, n = self._sum_program(field, len(shards))(
                    slices, filt
                )
        pend = _Pending(
            [pos, neg, n],
            lambda a: SumCount(ops.bsi.weigh_sum(a[0], a[1]), int(a[2])),
            route="mesh" if mesh else "device",
        )
        return pend if lazy else pend.resolve_now()

    def _execute_min_max(
        self, idx: Index, call: Call, shards: list[int], want_max: bool,
        lazy: bool = False, host: bool = False, mesh: bool = False,
    ):
        field = self._agg_field(idx, call)
        if host:
            value, n = self.compiler.host.min_max(
                idx, field, call, shards, want_max
            )
            return SumCount(value, n)
        slices = self._bsi_stacked(idx, field, shards)
        if mesh:
            # per-device-block extremes, all-gathered: finish() below
            # merges them exactly like per-shard partials (min/max with
            # count merges associatively over disjoint column blocks)
            mode = self.compiler.mesh_mode(len(shards))
            eng = self.compiler.mesh_engine
            fplan = self._filter_plan(idx, call, shards, mesh_mode=mode)
            if fplan is not None:
                frun, farrays, fscalars, fskey = fplan
                key = (
                    "mesh_minmax", len(shards), field.bit_depth, want_max,
                    mode, fskey,
                )
                prog = self.compiler.program(
                    key, lambda: eng.minmax_tree(want_max, mode, frun=frun)
                )
                values, counts = self.compiler._mesh_dispatch(
                    "minmax", key, prog, slices, farrays, fscalars
                )
            else:
                key = (
                    "mesh_minmax", len(shards), field.bit_depth, want_max,
                    mode,
                )
                prog = self.compiler.program(
                    key, lambda: eng.minmax_tree(want_max, mode)
                )
                values, counts = self.compiler._mesh_dispatch(
                    "minmax", key, prog, slices,
                    self.compiler.ones(len(shards)),
                )
        else:
            vmapped = jax.vmap(
                lambda ss, ff: ops.bsi.min_max(ss, ff, want_max=want_max),
                in_axes=(1, 0),
            )
            fplan = self._filter_plan(idx, call, shards)
            if fplan is not None:
                frun, farrays, fscalars, fskey = fplan
                values, counts = self.compiler.run_program(
                    ("minmax", len(shards), field.bit_depth, want_max, fskey),
                    lambda: jax.jit(
                        lambda s, fa, fs: vmapped(s, frun(fa, fs))
                    ),
                    slices,
                    farrays,
                    fscalars,
                )
            else:
                values, counts = self.compiler.run_program(
                    ("minmax", len(shards), field.bit_depth, want_max),
                    lambda: jax.jit(lambda s, f: vmapped(s, f)),
                    slices,
                    self.compiler.ones(len(shards)),
                )

        def finish(a):
            best, best_count = None, 0
            for v, n in zip(a[0].tolist(), a[1].tolist()):
                if n == 0:
                    continue
                if best is None or (v > best if want_max else v < best):
                    best, best_count = v, n
                elif v == best:
                    best_count += n
            return SumCount(best if best is not None else 0, best_count)

        pend = _Pending(
            [values, counts], finish, route="mesh" if mesh else "device"
        )
        return pend if lazy else pend.resolve_now()

    def _execute_topn(
        self, idx: Index, call: Call, shards: list[int], lazy: bool = False,
        host: bool = False, mesh: bool = False,
    ):
        field = self._field(idx, self._call_field_name(call))
        n = call.arg("n")
        ids = call.arg("ids")
        # internal (cluster fan-out) arg: return only rows whose LOCAL
        # count reaches the floor — the coordinator's bounded final TopN
        # pass (cluster._topn_two_phase) uses it so the worst-case
        # cross-node transfer is O(rows above the proven cutoff), never
        # every nonzero row
        min_count = call.arg("minCount")
        attr_name = call.arg("attrName")
        attr_values = call.arg("attrValues")
        if attr_name is not None and not attr_values:
            raise ExecutionError("TopN() attrName requires attrValues")

        if host:
            pairs = self.compiler.host.topn_pairs(
                idx, field, call, shards,
                list(ids) if ids is not None else None,
            )
            return self._topn_finish(
                field, pairs, n, attr_name, attr_values, min_count
            )
        try:
            matrix, n_rows = self.compiler.stacks.matrix(
                idx, field, VIEW_STANDARD, shards
            )
        except StackOverBudget:
            # streamed (over-budget) path: chunk readbacks are the
            # streaming discipline itself, so it stays synchronous; the
            # filter materializes ONCE and is reused across every chunk
            # (mesh route included — the stream IS the fallback)
            filt = self._filter_device(idx, call, shards)
            pairs = self._topn_chunked(
                idx, field, shards, filt, ids=ids
            )
            return self._topn_finish(
                field, pairs, n, attr_name, attr_values, min_count
            )
        mesh_mode = self.compiler.mesh_mode(len(shards)) if mesh else None
        fplan = self._filter_plan(idx, call, shards, mesh_mode=mesh_mode)
        if ids is not None:
            row_ids = jnp.asarray(ids, jnp.int32)
            if mesh:
                eng = self.compiler.mesh_engine
                filtered = fplan is not None
                key = ("mesh_topn_ids", len(shards), mesh_mode) + (
                    (fplan[3],) if filtered else ()
                )
                prog = self.compiler.program(
                    key,
                    lambda: eng.topn_tree(
                        mesh_mode,
                        filtered,
                        True,
                        frun=fplan[0] if filtered else None,
                    ),
                )
                if filtered:
                    counts = self.compiler._mesh_dispatch(
                        "topn", key, prog, matrix, row_ids, fplan[1], fplan[2]
                    )
                else:
                    counts = self.compiler._mesh_dispatch(
                        "topn", key, prog, matrix, row_ids
                    )
            elif fplan is not None:
                frun, farrays, fscalars, fskey = fplan
                counts = self.compiler.run_program(
                    ("topn_ids", len(shards), fskey),
                    lambda: jax.jit(
                        lambda m, r, fa, fs: jax.vmap(
                            ops.topn.candidate_counts, in_axes=(1, None, 0)
                        )(m, r, frun(fa, fs))
                        .astype(jnp.int64)
                        .sum(axis=0)
                    ),
                    matrix,
                    row_ids,
                    farrays,
                    fscalars,
                )
            else:
                counts = self.compiler.run_program(
                    ("topn_ids", len(shards)),
                    lambda: jax.jit(
                        lambda m, r: jnp.sum(
                            ops.popcount_rows(
                                jnp.take(
                                    m, r, axis=0, mode="fill", fill_value=0
                                )
                            ).astype(jnp.int64),
                            axis=1,
                        )
                    ),
                    matrix,
                    row_ids,
                )

            def finish(a):
                pairs = [
                    (int(r), int(c)) for r, c in zip(ids, a[0].tolist()) if c > 0
                ]
                return self._topn_finish(
                    field, pairs, n, attr_name, attr_values, min_count
                )

        else:
            if mesh:
                eng = self.compiler.mesh_engine
                filtered = fplan is not None
                key = ("mesh_topn", len(shards), mesh_mode) + (
                    (fplan[3],) if filtered else ()
                )
                prog = self.compiler.program(
                    key,
                    lambda: eng.topn_tree(
                        mesh_mode,
                        filtered,
                        False,
                        frun=fplan[0] if filtered else None,
                    ),
                )
                if filtered:
                    counts = self.compiler._mesh_dispatch(
                        "topn", key, prog, matrix, fplan[1], fplan[2]
                    )
                else:
                    counts = self.compiler._mesh_dispatch(
                        "topn", key, prog, matrix
                    )
            elif fplan is not None:
                frun, farrays, fscalars, fskey = fplan
                # filter computes INSIDE this program — no separate
                # dispatch, no [S, W] HBM round trip
                counts = self.compiler.run_program(
                    ("topn", len(shards), fskey),
                    lambda: jax.jit(
                        lambda m, fa, fs: ops.popcount_rows(
                            m & frun(fa, fs)[None]
                        )
                        .astype(jnp.int64)
                        .sum(axis=1)
                    ),
                    matrix,
                    farrays,
                    fscalars,
                )
            else:
                # no filter ⇒ no AND at all (the old path ANDed a
                # materialized all-ones array — pure HBM traffic)
                counts = self.compiler.run_program(
                    ("topn", len(shards)),
                    lambda: jax.jit(
                        lambda m: ops.popcount_rows(m)
                        .astype(jnp.int64)
                        .sum(axis=1)
                    ),
                    matrix,
                )

            def finish(a):
                nz = np.flatnonzero(a[0])
                pairs = [(int(r), int(a[0][r])) for r in nz.tolist()]
                return self._topn_finish(
                    field, pairs, n, attr_name, attr_values, min_count
                )

        pend = _Pending([counts], finish, route="mesh" if mesh else "device")
        return pend if lazy else pend.resolve_now()

    @staticmethod
    def _topn_finish(
        field: Field, pairs: list, n, attr_name, attr_values, min_count=None
    ) -> list[dict]:
        if min_count is not None:
            pairs = [(r, c) for r, c in pairs if c >= min_count]
        if attr_name is not None:
            allowed = set(attr_values)
            pairs = [
                (r, c)
                for r, c in pairs
                if field.row_attrs.attrs(r).get(attr_name) in allowed
            ]
        pairs.sort(key=lambda rc: (-rc[1], rc[0]))
        if n is not None:
            pairs = pairs[:n]
        out = []
        for rid, c in pairs:
            entry = {"id": rid, "count": c}
            if field.options.keys:
                entry["key"] = field.row_keys.translate_id(rid) or str(rid)
            out.append(entry)
        return out

    def _topn_chunked(
        self, idx: Index, field: Field, shards: list[int], filt, ids=None
    ) -> list:
        """TopN for over-budget (high-cardinality) fields: stream row
        chunks host-roaring → device, count, discard — device memory stays
        within the hot budget while every row is still counted EXACTLY
        (SURVEY §7 hard part (e); reference: fragment.go top full scan)."""
        view = field.view(VIEW_STANDARD)
        rows = list(ids) if ids is not None else self._rows_of_field(field, shards)
        if not rows:
            return []
        stacks = self.compiler.stacks
        chunk = stacks.hot_capacity(len(shards))
        frags = [view.fragment(s) if view else None for s in shards]
        prog = self.compiler.wrapped_program(
            ("topn_chunk", len(shards)),
            lambda: jax.jit(
                # g [C,S,W] row-major chunk, f [S,W] → int64[C]
                lambda g, f: jnp.sum(
                    ops.popcount_rows(g & f[None]).astype(jnp.int64),
                    axis=1,
                )
            ),
        )
        pairs: list = []
        for lo in range(0, len(rows), chunk):
            sub = rows[lo : lo + chunk]
            host = np.zeros(
                (len(sub), len(shards), WORDS_PER_SHARD), dtype=np.uint32
            )
            for i, frag in enumerate(frags):
                if frag is None:
                    continue
                for j, r in enumerate(sub):
                    host[j, i] = frag.row_packed(r)
            counts = np.asarray(prog(jnp.asarray(host), filt))
            for j, r in enumerate(sub):
                if counts[j] > 0:
                    pairs.append((int(r), int(counts[j])))
        return pairs

    def _rows_of_field(self, field: Field, shards: list[int]) -> list[int]:
        rows: set[int] = set()
        view = field.view(VIEW_STANDARD)
        if view is None:
            return []
        for s in shards:
            frag = view.fragment(s)
            if frag is not None:
                rows.update(frag.row_ids())
        return sorted(rows)

    def _execute_rows(self, idx: Index, call: Call, shards: list[int]) -> dict:
        field = self._field(idx, self._call_field_name(call))
        rows = self._rows_of_field(field, shards)
        rids = call.arg("ids")
        if rids is not None:
            want = set(rids)
            rows = [r for r in rows if r in want]
        col = call.arg("column")
        if col is not None:
            col_id = self._col_id(idx, col)
            shard = col_id // SHARD_WIDTH
            view = field.view(VIEW_STANDARD)
            frag = view.fragment(shard) if view else None
            rows = [
                r for r in rows if frag is not None and frag.contains(r, col_id)
            ]
        previous = call.arg("previous")
        if previous is not None:
            prev_id = self._row_id(field, previous)
            rows = [r for r in rows if r > (prev_id if prev_id is not None else -1)]
        limit = call.arg("limit")
        if limit is not None:
            rows = rows[:limit]
        if field.options.keys:
            return {
                "rows": rows,
                "keys": [field.row_keys.translate_id(r) or str(r) for r in rows],
            }
        return {"rows": rows}

    def _gb_programs(self, mesh_mode: str | None):
        """(gb_counts, gb_masks) program callables for one GroupBy
        execution: the single-program jitted pair, or the mesh engine's
        shard_map pair (same bodies, psum merge tree) when the query
        routed mesh — every call site below stays engine-agnostic."""
        if mesh_mode is None:
            gbc = lambda masks, m, rows: self.compiler.call_program(
                ("gb_counts",), _gb_counts, masks, m, rows
            )
            gbm = lambda masks, m, g_idx, row_sel: self.compiler.call_program(
                ("gb_masks",), _gb_masks, masks, m, g_idx, row_sel
            )
            return gbc, gbm
        eng = self.compiler.mesh_engine
        ckey = ("mesh_gb_counts", mesh_mode)
        cprog = self.compiler.program(
            ckey, lambda: eng.groupby_counts_tree(mesh_mode)
        )
        mkey = ("mesh_gb_masks", mesh_mode)
        mprog = self.compiler.program(
            mkey, lambda: eng.groupby_masks_tree(mesh_mode)
        )
        gbc = lambda masks, m, rows: self.compiler._mesh_dispatch(
            "groupby", ckey, cprog, masks, m, rows
        )
        gbm = lambda masks, m, g_idx, row_sel: self.compiler._mesh_dispatch(
            "groupby", mkey, mprog, masks, m, g_idx, row_sel
        )
        return gbc, gbm

    def _execute_group_by(
        self, idx: Index, call: Call, shards: list[int], lazy: bool = False,
        host: bool = False, mesh: bool = False,
    ):
        if not call.children or any(ch.name != "Rows" for ch in call.children):
            raise ExecutionError("GroupBy() takes Rows() calls")
        limit = call.arg("limit")
        filter_call = call.arg("filter")
        if filter_call is not None and not isinstance(filter_call, Call):
            raise ExecutionError("GroupBy filter must be a call")
        aggregate = call.arg("aggregate")
        if aggregate is not None and not (
            isinstance(aggregate, Call) and aggregate.name == "Sum"
        ):
            raise ExecutionError("GroupBy aggregate must be Sum(field=...)")
        agg_field = self._agg_field(idx, aggregate) if aggregate is not None else None

        fields: list[Field] = []
        row_lists: list[list[int]] = []
        for ch in call.children:
            f = self._field(idx, self._call_field_name(ch))
            fields.append(f)
            rows = self._rows_of_field(f, shards)
            rids = ch.arg("ids")
            if rids is not None:
                # explicit row universe — the cluster coordinator pins the
                # GLOBAL first-L rows here so per-node expansion agrees
                # (see cluster._pin_groupby_rows)
                want = set(rids)
                rows = [r for r in rows if r in want]
            prev = ch.arg("previous")
            if prev is not None:
                prev_id = self._row_id(f, prev)
                rows = [r for r in rows if r > (prev_id if prev_id is not None else -1)]
            rlimit = ch.arg("limit")
            if rlimit is not None:
                rows = rows[:rlimit]
            row_lists.append(rows)

        if host:
            # one engine, same spec: identical row universes and emission
            # order, so host/device results match entry for entry
            return self.compiler.host.group_by(
                idx, fields, row_lists, filter_call, agg_field, limit, shards
            )

        agg_slices = (
            self._bsi_stacked(idx, agg_field, shards) if agg_field is not None else None
        )
        matrices = []
        for f in fields:
            try:
                matrices.append(
                    self.compiler.stacks.matrix(idx, f, VIEW_STANDARD, shards)[0]
                )
            except StackOverBudget:
                # over-budget (high-cardinality) level: no resident stack —
                # counts and masks stream row chunks host→device instead
                # (same discipline as _topn_chunked; VERDICT r2 item 4)
                matrices.append(None)

        mesh_mode = self.compiler.mesh_mode(len(shards)) if mesh else None
        gb_counts_call, gb_masks_call = self._gb_programs(mesh_mode)
        if filter_call is not None:
            if mesh_mode is not None:
                base_mask = self.compiler.mesh_bitmap_device(
                    idx, filter_call, shards
                )
            else:
                base_mask = self._filter_device(
                    idx, Call("_", {}, [filter_call]), shards
                )
        else:
            base_mask = self.compiler.ones(len(shards))

        if (
            aggregate is None
            and all(m is not None for m in matrices)
            and all(row_lists)
        ):
            fused = self._groupby_fused(
                fields, row_lists, matrices, base_mask, limit, len(shards),
                gb_counts_call, gb_masks_call, route_mesh=mesh_mode is not None,
            )
            if fused is not None:
                return fused if lazy else fused.resolve_now()

        # Level-synchronous evaluation: a whole nesting level runs in TWO
        # device dispatches — (1) counts of every (surviving group ×
        # candidate row) pair, (2) materialization of the surviving
        # groups' masks — instead of the reference's one-executor-pass-
        # per-group (executor.go executeGroupBy; round-1 code dispatched
        # one program per candidate row). Device memory for the [G, S, W]
        # group-mask tensor is bounded by GROUPBY_MASK_BUDGET: when a
        # level survives more groups than fit, the pair list is processed
        # in mask-budget-sized chunks depth-first (order — and therefore
        # limit semantics — is preserved because chunks run in pair
        # order). Shapes pad to powers of two so recompiles stay rare.
        n_shards = len(shards)
        # floor to a power of two so padded chunks never exceed the
        # budget (p_pad ≤ chunk_cap), and pow2 shapes keep XLA retraces
        # to one compile per bucket
        chunk_cap = max(
            1, self._gb_budget() // (n_shards * WORDS_PER_SHARD * 4)
        )
        chunk_cap = 1 << (chunk_cap.bit_length() - 1)

        results: list[dict] = []
        sum_prog = None
        if agg_slices is not None:
            if mesh_mode is not None:
                eng = self.compiler.mesh_engine
                gskey = (
                    "mesh_gb_sums", n_shards, agg_field.bit_depth, mesh_mode,
                )
                gsp = self.compiler.program(
                    gskey,
                    lambda: eng.grouped_sum_tree(self._sum_fn, mesh_mode),
                )
                sum_prog = lambda s, m: self.compiler._mesh_dispatch(
                    "groupby", gskey, gsp, s, m
                )
            else:
                sum_prog = self._grouped_sum_program(agg_field, n_shards)

        def emit(groups: list[tuple], counts: np.ndarray, masks) -> None:
            start = len(results)
            for grp, c in zip(groups, counts.tolist()):
                results.append(
                    {
                        "group": [
                            {"field": f.name, "rowID": rid} for f, rid in grp
                        ],
                        "count": int(c),
                    }
                )
            if sum_prog is not None:
                pos, neg, _n = (
                    np.asarray(x) for x in sum_prog(agg_slices, masks)
                )
                for i in range(len(groups)):
                    results[start + i]["sum"] = ops.bsi.weigh_sum(pos[i], neg[i])

        def _level_frags(level: int) -> list:
            view = fields[level].view(VIEW_STANDARD)
            return [view.fragment(s) if view else None for s in shards]

        # per-execution LRU of host-packed rows: the counts pass and the
        # mask pass both need a streamed level's rows, and a row recurs
        # across pair chunks once per surviving parent group — entries are
        # bounded to chunk_cap so the cache stays within the same budget
        # as the mask tensor itself
        from collections import OrderedDict

        pack_cache: OrderedDict[tuple[int, int], np.ndarray] = OrderedDict()

        def _pack_rows(level: int, frags: list, rows: list[int], k_pad: int) -> np.ndarray:
            """Host-pack [k_pad, S, W] (row-major, like resident stacks)
            for a streamed level's row subset; padding rows stay zero so
            their counts/masks are zero."""
            host = np.zeros((k_pad, n_shards, WORDS_PER_SHARD), dtype=np.uint32)
            for j, r in enumerate(rows):
                key = (level, r)
                got = pack_cache.get(key)
                if got is None:
                    got = np.stack(
                        [
                            frag.row_packed(r)
                            if frag is not None
                            else np.zeros(WORDS_PER_SHARD, dtype=np.uint32)
                            for frag in frags
                        ]
                    )
                    pack_cache[key] = got
                    while len(pack_cache) > chunk_cap:
                        pack_cache.popitem(last=False)
                else:
                    pack_cache.move_to_end(key)
                host[j] = got
            return host

        def _level_counts(level: int, masks, n_groups: int) -> np.ndarray:
            """int64[n_groups, len(rows_l)] — resident stack when the level
            fits the budget, streamed row chunks otherwise (exactness and
            (g, k) output order are identical either way)."""
            rows_l = row_lists[level]
            m = matrices[level]
            if m is not None:
                k_pad = _pow2(len(rows_l))
                rows_arr = _pad_row_ids(rows_l, k_pad)
                return np.asarray(
                    gb_counts_call(masks, m, jnp.asarray(rows_arr))
                )[:n_groups, : len(rows_l)]
            frags = _level_frags(level)
            hot = self.compiler.stacks.hot_capacity(n_shards)
            parts = []
            for lo in range(0, len(rows_l), hot):
                sub = rows_l[lo : lo + hot]
                k_pad = _pow2(len(sub))
                host = _pack_rows(level, frags, sub, k_pad)
                parts.append(
                    np.asarray(
                        gb_counts_call(
                            masks,
                            jnp.asarray(host),
                            jnp.arange(k_pad, dtype=jnp.int32),
                        )
                    )[:n_groups, : len(sub)]
                )
            return np.concatenate(parts, axis=1)

        def _pair_masks(level: int, masks, chunk: np.ndarray):
            """Materialize one pair-chunk's group masks. Streamed levels
            pack only the chunk's distinct rows (≤ chunk_cap ≤ the mask
            budget) and select them by local index."""
            rows_l = row_lists[level]
            m = matrices[level]
            p_pad = _pow2(chunk.shape[0])
            g_idx = np.zeros(p_pad, dtype=np.int32)
            row_sel = np.full(p_pad, -1, dtype=np.int32)
            g_idx[: chunk.shape[0]] = chunk[:, 0]
            if m is None:
                uniq_k = np.unique(chunk[:, 1])
                m = jnp.asarray(
                    _pack_rows(
                        level,
                        _level_frags(level),
                        [rows_l[k] for k in uniq_k.tolist()],
                        _pow2(uniq_k.size),
                    )
                )
                row_sel[: chunk.shape[0]] = np.searchsorted(uniq_k, chunk[:, 1])
            else:
                row_sel[: chunk.shape[0]] = [rows_l[k] for k in chunk[:, 1]]
            return gb_masks_call(
                masks, m, jnp.asarray(g_idx), jnp.asarray(row_sel)
            )

        def expand(level: int, masks, groups: list[tuple]) -> None:
            if limit is not None and len(results) >= limit:
                return
            rows_l = row_lists[level]
            cnp = _level_counts(level, masks, len(groups))
            pairs = np.argwhere(cnp > 0)  # (g-major, k-minor) = lexicographic
            last = level == len(fields) - 1
            if last and limit is not None:
                pairs = pairs[: limit - len(results)]
            for lo in range(0, pairs.shape[0], chunk_cap):
                chunk = pairs[lo : lo + chunk_cap]
                sub_groups = [
                    groups[g] + ((fields[level], rows_l[k]),)
                    for g, k in chunk.tolist()
                ]
                if last and sum_prog is None:
                    # counts suffice — skip materializing final masks
                    emit(sub_groups, cnp[chunk[:, 0], chunk[:, 1]], None)
                else:
                    # p_pad-padded: padding entries are all-zero masks
                    # (g_idx 0 & row -1 → 0) and count 0, and a stable
                    # pow2 shape avoids per-G recompiles
                    sub_masks = _pair_masks(level, masks, chunk)
                    if last:
                        emit(
                            sub_groups, cnp[chunk[:, 0], chunk[:, 1]], sub_masks
                        )
                    else:
                        expand(level + 1, sub_masks, sub_groups)
                if limit is not None and len(results) >= limit:
                    return

        if all(row_lists):
            expand(0, base_mask[None], [()])
        return results

    def _groupby_fused(
        self, fields, row_lists, matrices, base_mask, limit, n_shards,
        gb_counts_call, gb_masks_call, route_mesh: bool = False,
    ):
        """All-pairs GroupBy: fold every level but the last into one
        [G, S, W] pair-mask tensor with zero intermediate readbacks, then
        count the last level's rows against it — the whole query is one
        dispatch chain ending in a single DEFERRED [G, K] readback
        (_Pending), so a GroupBy costs the same one transport RTT as a
        Count (VERDICT r3 weak #3: sync GroupBy measured BELOW the CPU
        baseline because each level paid a full sync RTT).

        Pruning falls out of the algebra instead of host control flow: a
        padding row (-1) or an empty parent gathers an all-zero mask, so
        every invalid/empty combination surfaces as count 0 and the
        resolve-time argwhere(>0) drops it. Emission order is argwhere's
        row-major order = nested ascending row order, so `limit` cuts
        identically to the level-synchronous path.

        Returns None when the folded tensor would exceed
        GROUPBY_MASK_BUDGET — the level-synchronous path prunes via
        surviving groups and streams chunks, trading readbacks for
        memory. Aggregate-Sum queries also take that path (sums need the
        surviving groups' masks, which this path never materializes
        host-side)."""
        kp = [_pow2(len(r)) for r in row_lists]
        G = 1
        masks = base_mask[None]
        for lvl in range(len(fields) - 1):
            g_new = G * kp[lvl]
            if g_new * n_shards * WORDS_PER_SHARD * 4 > self._gb_budget():
                return None
            rows_arr = _pad_row_ids(row_lists[lvl], kp[lvl])
            g_idx = np.repeat(np.arange(G, dtype=np.int32), kp[lvl])
            masks = gb_masks_call(
                masks,
                matrices[lvl],
                jnp.asarray(g_idx),
                jnp.asarray(np.tile(rows_arr, G)),
            )
            G = g_new
        last = len(fields) - 1
        rows_arr = _pad_row_ids(row_lists[last], kp[last])
        counts = gb_counts_call(masks, matrices[last], jnp.asarray(rows_arr))

        def finish(a):
            cnt = a[0]  # [G, kp[last]]
            results: list[dict] = []
            for flat, k in np.argwhere(cnt > 0).tolist():
                if limit is not None and len(results) >= limit:
                    break
                idxs = [k]
                rem = flat
                for lvl in range(last - 1, -1, -1):
                    idxs.append(rem % kp[lvl])
                    rem //= kp[lvl]
                idxs.reverse()
                results.append(
                    {
                        "group": [
                            {"field": fields[lvl].name,
                             "rowID": row_lists[lvl][j]}
                            for lvl, j in enumerate(idxs)
                        ],
                        "count": int(cnt[flat, k]),
                    }
                )
            return results

        return _Pending(
            [counts], finish, route="mesh" if route_mesh else "device"
        )

    # ------------------------------------------------------------ writes
    def _execute_includes_column(
        self, idx: Index, call: Call, shards: list[int], host: bool = False
    ) -> bool:
        """IncludesColumn(bitmap, column=N) → bool (reference:
        executor.go executeIncludesColumnCall). Only the column's own
        shard is evaluated — one [1, W] program instead of a full scan."""
        if len(call.children) != 1:
            raise ExecutionError("IncludesColumn() takes exactly one call")
        col = call.arg("column")
        if col is None:
            raise ExecutionError("IncludesColumn() requires a column argument")
        col_id = self._col_id(idx, col, create=False)
        if col_id is None:
            return False
        shard = col_id // SHARD_WIDTH
        if shard not in shards:
            return False
        offset = col_id % SHARD_WIDTH
        if host:
            return self.compiler.host.includes_column(idx, call, shard, offset)
        words = self._bitmap_words(idx, call.children[0], [shard])[0]
        return bool((int(words[offset // 32]) >> (offset % 32)) & 1)

    def _execute_write(self, idx: Index, call: Call) -> Any:
        name = call.name
        if name == "Set":
            return self._execute_set(idx, call)
        if name == "Clear":
            return self._execute_clear(idx, call)
        if name == "ClearRow":
            return self._execute_clear_row(idx, call)
        if name == "Store":
            return self._execute_store(idx, call)
        if name == "SetRowAttrs":
            return self._execute_set_row_attrs(idx, call)
        if name == "SetColumnAttrs":
            return self._execute_set_column_attrs(idx, call)
        raise ExecutionError(f"unknown write call {name!r}")

    def _set_args(self, idx: Index, call: Call) -> tuple[int, Field, Any, datetime | None]:
        if not call.pos_args:
            raise ExecutionError(f"{call.name}() needs a column argument")
        col = self._col_id(idx, call.pos_args[0], create=call.name == "Set")
        ts = None
        for extra in call.pos_args[1:]:
            coerced = coerce_timestamp(extra)
            if coerced is not None:
                ts = coerced
            else:
                raise ExecutionError(f"unexpected argument {extra!r}")
        fa = call.field_arg()
        if fa is None:
            raise ExecutionError(f"{call.name}() needs a field=row argument")
        fname, row = fa
        return col, self._field(idx, fname), row, ts

    def _execute_set(self, idx: Index, call: Call) -> bool:
        col, field, row, ts = self._set_args(idx, call)
        if field.options.field_type == FIELD_INT:
            if not isinstance(row, int) or isinstance(row, bool):
                raise ExecutionError("int field Set() needs an integer value")
            changed = field.set_value(col, row)
        else:
            row_id = self._row_id(field, row, create=True)
            changed = field.set_bit(row_id, col, timestamp=ts)
        idx.mark_columns_exist(np.array([col], dtype=np.uint64))
        return changed

    def _execute_clear(self, idx: Index, call: Call) -> bool:
        col, field, row, _ts = self._set_args(idx, call)
        if field.options.field_type == FIELD_INT:
            return field.clear_value(col)
        row_id = self._row_id(field, row)
        if row_id is None:
            return False
        return field.clear_bit(row_id, col)

    def _execute_clear_row(self, idx: Index, call: Call) -> bool:
        fa = call.field_arg()
        if fa is None:
            raise ExecutionError("ClearRow() needs a field=row argument")
        fname, row = fa
        field = self._field(idx, fname)
        if field.options.field_type in (FIELD_INT,):
            raise ExecutionError("ClearRow() is not supported on int fields")
        row_id = self._row_id(field, row)
        if row_id is None:
            return False
        changed = False
        for view in field.views.values():
            for frag in view.fragments.values():
                changed |= frag.clear_row(row_id)
        return changed

    def _execute_store(self, idx: Index, call: Call) -> bool:
        if len(call.children) != 1:
            raise ExecutionError("Store() takes exactly one row call")
        fa = call.field_arg()
        if fa is None:
            raise ExecutionError("Store() needs a field=row argument")
        fname, row = fa
        field = self._field(idx, fname)
        row_id = self._row_id(field, row, create=True)
        shards = self._shards(idx, None)
        words = self._bitmap_words(idx, call.children[0], shards)
        for i, s in enumerate(shards):
            positions = unpack_words(words[i])
            frag = field.create_view_if_not_exists(
                VIEW_STANDARD
            ).create_fragment_if_not_exists(s)
            frag.set_row(row_id, positions.astype(np.uint64))
        return True

    def _execute_set_row_attrs(self, idx: Index, call: Call) -> None:
        if len(call.pos_args) < 2:
            raise ExecutionError("SetRowAttrs(field, row, attrs...) needs 2 args")
        field = self._field(idx, call.pos_args[0])
        row_id = self._row_id(field, call.pos_args[1], create=True)
        field.row_attrs.set_attrs(row_id, dict(call.args))
        return None

    def _execute_set_column_attrs(self, idx: Index, call: Call) -> None:
        if len(call.pos_args) < 1:
            raise ExecutionError("SetColumnAttrs(col, attrs...) needs a column")
        col = self._col_id(idx, call.pos_args[0], create=True)
        idx.column_attrs.set_attrs(col, dict(call.args))
        return None
