"""Query executor: PQL AST → device programs over sharded fragments.

Reference: executor.go (executor.Execute, executeCall, executeBitmapCall,
executeCount, executeTopN, executeSum/Min/Max, executeGroupBy, executeRows,
executeSet/Clear…, mapReduce, mapperLocal/mapperRemote). Redesigned for
TPU:

- a bitmap expression evaluates per shard as a chain of elementwise bitwise
  ops over the fragment's dense packed matrix — XLA fuses the chain into a
  single kernel; counts are fused op+popcount reductions;
- the reference's HTTP scatter-gather reduce (mapReduce → mapperRemote)
  becomes, on a single host, a loop over resident shards; the cluster layer
  fans out non-local shards (see pilosa_tpu.parallel / server), and the
  mesh path executes all shards in one pjit program with psum reductions;
- TopN is EXACT in one pass (per-row masked popcount over the resident
  matrix + top_k) instead of the reference's approximate cache-fed phase 1;
  the two-phase recount survives only for the ids= form. This is a
  deliberate departure: the rank cache exists because the reference cannot
  afford full row scans per query; the dense device matrix can.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any

import numpy as np

from pilosa_tpu import ops
from pilosa_tpu.core import (
    BSI_OFFSET,
    EXISTENCE_FIELD,
    FIELD_BOOL,
    FIELD_INT,
    FIELD_MUTEX,
    FIELD_TIME,
    VIEW_BSI,
    VIEW_STANDARD,
    Field,
    Holder,
    Index,
)
from pilosa_tpu.core.timequantum import views_by_time_range
from pilosa_tpu.executor.row import RowResult
from pilosa_tpu.pql import Call, Condition, PQLError, parse
from pilosa_tpu.roaring import unpack_words
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD

BITMAP_CALLS = {
    "Row",
    "Range",
    "Union",
    "Intersect",
    "Difference",
    "Xor",
    "Not",
    "All",
    "Shift",
}
WRITE_CALLS = {
    "Set",
    "Clear",
    "ClearRow",
    "Store",
    "SetRowAttrs",
    "SetColumnAttrs",
}


class ExecutionError(ValueError):
    pass


class SumCount(dict):
    """Sum/Min/Max result: {"value": v, "count": n} (reference: ValCount)."""

    def __init__(self, value: int, count: int):
        super().__init__(value=int(value), count=int(count))


class Executor:
    def __init__(self, holder: Holder):
        self.holder = holder

    # ------------------------------------------------------------ entry
    def execute(
        self,
        index_name: str,
        query: str | list[Call],
        shards: list[int] | None = None,
    ) -> list[Any]:
        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecutionError(f"index {index_name!r} not found")
        calls = parse(query) if isinstance(query, str) else query
        return [self._execute_call(idx, c, shards) for c in calls]

    def _shards(self, idx: Index, shards: list[int] | None) -> list[int]:
        if shards is not None:
            return sorted(shards)
        avail = idx.available_shards()
        return sorted(avail) if avail else [0]

    def _execute_call(self, idx: Index, call: Call, shards: list[int] | None) -> Any:
        name = call.name
        if name == "Options":
            if len(call.children) != 1:
                raise ExecutionError("Options() takes exactly one call")
            opt_shards = call.arg("shards", shards)
            return self._execute_call(idx, call.children[0], opt_shards)
        if name in WRITE_CALLS:
            return self._execute_write(idx, call)
        shard_list = self._shards(idx, shards)
        if name in BITMAP_CALLS:
            segs = {s: self._bitmap(idx, call, s) for s in shard_list}
            res = RowResult(segs)
            self._attach_keys(idx, res)
            return res
        if name == "Count":
            return self._execute_count(idx, call, shard_list)
        if name == "Sum":
            return self._execute_sum(idx, call, shard_list)
        if name in ("Min", "Max"):
            return self._execute_min_max(idx, call, shard_list, name == "Max")
        if name == "TopN":
            return self._execute_topn(idx, call, shard_list)
        if name == "Rows":
            return self._execute_rows(idx, call, shard_list)
        if name == "GroupBy":
            return self._execute_group_by(idx, call, shard_list)
        raise ExecutionError(f"unknown call {name!r}")

    # ----------------------------------------------------------- helpers
    def _field(self, idx: Index, name: str) -> Field:
        f = idx.field(name)
        if f is None:
            raise ExecutionError(f"field {name!r} not found")
        return f

    def _row_id(self, field: Field, row: Any, create: bool = False) -> int | None:
        """Resolve a row arg (int or string key) to a row ID."""
        if isinstance(row, bool):
            return int(row)
        if isinstance(row, int):
            return row
        if isinstance(row, str):
            if not field.options.keys:
                raise ExecutionError(
                    f"field {field.name!r} does not use string keys"
                )
            return field.row_keys.translate_key(row, create=create)
        raise ExecutionError(f"bad row value {row!r}")

    def _col_id(self, idx: Index, col: Any, create: bool = False) -> int | None:
        if isinstance(col, int) and not isinstance(col, bool):
            return col
        if isinstance(col, str):
            if not idx.options.keys:
                raise ExecutionError(f"index {idx.name!r} does not use string keys")
            return idx.column_keys.translate_key(col, create=create)
        raise ExecutionError(f"bad column value {col!r}")

    def _attach_keys(self, idx: Index, res: RowResult) -> None:
        if idx.options.keys:
            cols = res.columns().tolist()
            res.keys = [idx.column_keys.translate_id(c) or str(c) for c in cols]

    def _zeros(self):
        return np.zeros(WORDS_PER_SHARD, dtype=np.uint32)

    def _ones(self):
        return np.full(WORDS_PER_SHARD, 0xFFFFFFFF, dtype=np.uint32)

    def _call_field_name(self, call: Call) -> str:
        """field= arg or first positional (TopN/Rows/Sum style calls)."""
        fname = call.arg("field")
        if fname is None and call.pos_args:
            fname = call.pos_args[0]
        if fname is None:
            raise ExecutionError(f"{call.name}() needs a field argument")
        return fname

    def _frag_row_words(self, field: Field, view_name: str, shard: int, row: int):
        view = field.view(view_name)
        frag = view.fragment(shard) if view else None
        if frag is None:
            return self._zeros()
        m, n = frag.device_matrix()
        if row < 0 or row >= n:
            return self._zeros()
        return m[row]

    def _bsi_slices(self, field: Field, shard: int):
        """(slices uint32[2+depth, W]) for an int field's shard, or None."""
        view = field.view(VIEW_BSI)
        frag = view.fragment(shard) if view else None
        if frag is None:
            return None
        m, _n = frag.device_matrix()
        depth = field.bit_depth
        need = BSI_OFFSET + depth
        if m.shape[0] < need:
            pad = np.zeros((need - m.shape[0], m.shape[1]), dtype=np.uint32)
            m = np.concatenate([np.asarray(m), pad], axis=0)
        return m[:need]

    def _existence_words(self, idx: Index, shard: int):
        if not idx.options.track_existence:
            raise ExecutionError(
                "query requires existence tracking (index created with "
                "track_existence=false)"
            )
        ef = idx.field(EXISTENCE_FIELD)
        if ef is None:
            return self._zeros()
        return self._frag_row_words(ef, VIEW_STANDARD, shard, 0)

    # ------------------------------------------------------- bitmap eval
    def _bitmap(self, idx: Index, call: Call, shard: int):
        """Evaluate a bitmap call for one shard → uint32[W] (device)."""
        name = call.name
        if name in ("Row", "Range"):
            return self._bitmap_row(idx, call, shard)
        if name == "Union":
            out = self._zeros()
            for ch in call.children:
                out = ops.w_or(out, self._bitmap(idx, ch, shard))
            return out
        if name == "Intersect":
            if not call.children:
                raise ExecutionError("Intersect() needs at least one child")
            out = self._bitmap(idx, call.children[0], shard)
            for ch in call.children[1:]:
                out = ops.w_and(out, self._bitmap(idx, ch, shard))
            return out
        if name == "Difference":
            if not call.children:
                raise ExecutionError("Difference() needs at least one child")
            out = self._bitmap(idx, call.children[0], shard)
            for ch in call.children[1:]:
                out = ops.w_andnot(out, self._bitmap(idx, ch, shard))
            return out
        if name == "Xor":
            out = self._zeros()
            for ch in call.children:
                out = ops.w_xor(out, self._bitmap(idx, ch, shard))
            return out
        if name == "Not":
            if len(call.children) != 1:
                raise ExecutionError("Not() takes exactly one call")
            exists = self._existence_words(idx, shard)
            return ops.w_andnot(exists, self._bitmap(idx, call.children[0], shard))
        if name == "All":
            return self._existence_words(idx, shard)
        if name == "Shift":
            if len(call.children) != 1:
                raise ExecutionError("Shift() takes exactly one call")
            n = call.arg("n", 1)
            if not isinstance(n, int) or n < 0:
                raise ExecutionError(f"Shift() n must be a non-negative integer, got {n!r}")
            # per-shard shift: bits crossing the shard boundary are dropped
            # (same per-shard behavior as the reference's Shift)
            return ops.shift_words(self._bitmap(idx, call.children[0], shard), n)
        raise ExecutionError(f"{name!r} is not a bitmap call")

    def _bitmap_row(self, idx: Index, call: Call, shard: int):
        cond = call.condition()
        if cond is not None:
            fname, condition = cond
            field = self._field(idx, fname)
            if field.options.field_type != FIELD_INT:
                raise ExecutionError(f"field {fname!r} is not an int field")
            slices = self._bsi_slices(field, shard)
            if slices is None:
                if condition.op == "==" and condition.value is None:
                    return self._existence_words(idx, shard)
                return self._zeros()
            if condition.value is None:
                # null comparisons: f != null ⇒ has a value;
                # f == null ⇒ exists in the index but has no value
                exists = slices[0]
                if condition.op == "!=":
                    return exists
                if condition.op == "==":
                    return ops.w_andnot(self._existence_words(idx, shard), exists)
                raise ExecutionError(
                    f"null only supports ==/!= comparisons, got {condition.op!r}"
                )
            if condition.op == "between":
                lo, hi = condition.value
                return ops.bsi.between(slices, int(lo), int(hi))
            return ops.bsi.compare(slices, condition.op, int(condition.value))

        fa = call.field_arg()
        if fa is None:
            raise ExecutionError(f"Row() needs a field argument: {call!r}")
        fname, row = fa
        field = self._field(idx, fname)
        row_id = self._row_id(field, row)
        if row_id is None:
            return self._zeros()

        ts_from, ts_to = call.arg("from"), call.arg("to")
        if ts_from is not None or ts_to is not None:
            if field.options.field_type != FIELD_TIME:
                raise ExecutionError(f"field {fname!r} is not a time field")
            # bound open endpoints by the materialized buckets so a
            # fine-grained quantum never enumerates empty calendar views
            bounds = field.time_bounds()
            if bounds is None:
                return self._zeros()
            ts_from = ts_from if ts_from is not None else bounds[0]
            ts_to = ts_to if ts_to is not None else bounds[1]
            out = self._zeros()
            for view_name in views_by_time_range(
                VIEW_STANDARD, ts_from, ts_to, field.options.time_quantum
            ):
                out = ops.w_or(
                    out, self._frag_row_words(field, view_name, shard, row_id)
                )
            return out
        return self._frag_row_words(field, VIEW_STANDARD, shard, row_id)

    # ------------------------------------------------------- aggregates
    def _execute_count(self, idx: Index, call: Call, shards: list[int]) -> int:
        if len(call.children) != 1:
            raise ExecutionError("Count() takes exactly one call")
        total = 0
        for s in shards:
            total += int(ops.popcount(self._bitmap(idx, call.children[0], s)))
        return total

    def _filter_words(self, idx: Index, call: Call, shard: int):
        """Child-call filter for aggregates; all-ones when absent."""
        if call.children:
            return self._bitmap(idx, call.children[0], shard)
        return self._ones()

    def _agg_field(self, idx: Index, call: Call) -> Field:
        field = self._field(idx, self._call_field_name(call))
        if field.options.field_type != FIELD_INT:
            raise ExecutionError(f"field {field.name!r} is not an int field")
        return field

    def _execute_sum(self, idx: Index, call: Call, shards: list[int]) -> SumCount:
        field = self._agg_field(idx, call)
        total, n_total = 0, 0
        for s in shards:
            slices = self._bsi_slices(field, s)
            if slices is None:
                continue
            filt = self._filter_words(idx, call, s)
            pos, neg, n = ops.bsi.sum_counts(slices, filt)
            total += ops.bsi.weigh_sum(np.asarray(pos), np.asarray(neg))
            n_total += int(n)
        return SumCount(total, n_total)

    def _execute_min_max(
        self, idx: Index, call: Call, shards: list[int], want_max: bool
    ) -> SumCount:
        field = self._agg_field(idx, call)
        best, best_count = None, 0
        for s in shards:
            slices = self._bsi_slices(field, s)
            if slices is None:
                continue
            filt = self._filter_words(idx, call, s)
            v, n = ops.bsi.min_max(slices, filt, want_max=want_max)
            v, n = int(v), int(n)
            if n == 0:
                continue
            if best is None or (v > best if want_max else v < best):
                best, best_count = v, n
            elif v == best:
                best_count += n
        return SumCount(best if best is not None else 0, best_count)

    def _execute_topn(self, idx: Index, call: Call, shards: list[int]) -> list[dict]:
        field = self._field(idx, self._call_field_name(call))
        n = call.arg("n")
        ids = call.arg("ids")
        attr_name = call.arg("attrName")
        attr_values = call.arg("attrValues")
        if attr_name is not None and not attr_values:
            raise ExecutionError("TopN() attrName requires attrValues")

        # per-shard filtered counts over ALL rows, summed across shards —
        # exact in one pass (see module docstring)
        counts_by_row: dict[int, int] = {}
        for s in shards:
            view = field.view(VIEW_STANDARD)
            frag = view.fragment(s) if view else None
            if frag is None:
                continue
            m, n_rows = frag.device_matrix()
            filt = self._filter_words(idx, call, s)
            if ids is not None:
                row_ids = np.asarray(ids, dtype=np.int32)
                shard_counts = np.asarray(
                    ops.topn.candidate_counts(np.asarray(m), row_ids, filt)
                )
                for rid, c in zip(row_ids.tolist(), shard_counts.tolist()):
                    counts_by_row[rid] = counts_by_row.get(rid, 0) + int(c)
            else:
                shard_counts = np.asarray(ops.matrix_filter_counts(m, filt))[:n_rows]
                for rid in np.flatnonzero(shard_counts).tolist():
                    counts_by_row[rid] = counts_by_row.get(rid, 0) + int(
                        shard_counts[rid]
                    )

        pairs = [(rid, c) for rid, c in counts_by_row.items() if c > 0]
        if attr_name is not None:
            allowed = set(attr_values or [])
            pairs = [
                (rid, c)
                for rid, c in pairs
                if (field.row_attrs.attrs(rid).get(attr_name) in allowed)
            ]
        pairs.sort(key=lambda rc: (-rc[1], rc[0]))
        if n is not None:
            pairs = pairs[:n]
        out = []
        for rid, c in pairs:
            entry = {"id": rid, "count": c}
            if field.options.keys:
                entry["key"] = field.row_keys.translate_id(rid) or str(rid)
            out.append(entry)
        return out

    def _rows_of_field(self, field: Field, shards: list[int]) -> list[int]:
        rows: set[int] = set()
        view = field.view(VIEW_STANDARD)
        if view is None:
            return []
        for s in shards:
            frag = view.fragment(s)
            if frag is not None:
                rows.update(frag.row_ids())
        return sorted(rows)

    def _execute_rows(self, idx: Index, call: Call, shards: list[int]) -> dict:
        field = self._field(idx, self._call_field_name(call))
        rows = self._rows_of_field(field, shards)
        col = call.arg("column")
        if col is not None:
            col_id = self._col_id(idx, col)
            shard = col_id // SHARD_WIDTH
            view = field.view(VIEW_STANDARD)
            frag = view.fragment(shard) if view else None
            rows = [
                r for r in rows if frag is not None and frag.contains(r, col_id)
            ]
        previous = call.arg("previous")
        if previous is not None:
            prev_id = self._row_id(field, previous)
            rows = [r for r in rows if r > (prev_id if prev_id is not None else -1)]
        limit = call.arg("limit")
        if limit is not None:
            rows = rows[:limit]
        if field.options.keys:
            return {
                "rows": rows,
                "keys": [field.row_keys.translate_id(r) or str(r) for r in rows],
            }
        return {"rows": rows}

    def _execute_group_by(self, idx: Index, call: Call, shards: list[int]) -> list[dict]:
        if not call.children or any(ch.name != "Rows" for ch in call.children):
            raise ExecutionError("GroupBy() takes Rows() calls")
        limit = call.arg("limit")
        filter_call = call.arg("filter")
        aggregate = call.arg("aggregate")
        if aggregate is not None and not (
            isinstance(aggregate, Call) and aggregate.name == "Sum"
        ):
            raise ExecutionError("GroupBy aggregate must be Sum(field=...)")
        agg_field = self._agg_field(idx, aggregate) if aggregate is not None else None

        fields: list[Field] = []
        row_lists: list[list[int]] = []
        for ch in call.children:
            f = self._field(idx, self._call_field_name(ch))
            fields.append(f)
            rows = self._rows_of_field(f, shards)
            rlimit = ch.arg("limit")
            prev = ch.arg("previous")
            if prev is not None:
                prev_id = self._row_id(f, prev)
                rows = [r for r in rows if r > (prev_id if prev_id is not None else -1)]
            if rlimit is not None:
                rows = rows[:rlimit]
            row_lists.append(rows)

        results: list[dict] = []

        def recurse(level: int, group: list[tuple[Field, int]], masks: dict[int, Any]):
            if limit is not None and len(results) >= limit:
                return
            if level == len(fields):
                count = 0
                agg_total, agg_n = 0, 0
                for s in shards:
                    count += int(ops.popcount(masks[s]))
                    if agg_field is not None:
                        slices = self._bsi_slices(agg_field, s)
                        if slices is not None:
                            pos, neg, an = ops.bsi.sum_counts(slices, masks[s])
                            agg_total += ops.bsi.weigh_sum(
                                np.asarray(pos), np.asarray(neg)
                            )
                            agg_n += int(an)
                if count == 0:
                    return
                entry = {
                    "group": [
                        {"field": f.name, "rowID": rid} for f, rid in group
                    ],
                    "count": count,
                }
                if agg_field is not None:
                    entry["sum"] = agg_total
                results.append(entry)
                return
            f = fields[level]
            for rid in row_lists[level]:
                new_masks = {}
                nonzero = False
                for s in shards:
                    row_words = self._frag_row_words(f, VIEW_STANDARD, s, rid)
                    new_masks[s] = ops.w_and(masks[s], row_words)
                    if not nonzero and int(ops.popcount(new_masks[s])):
                        nonzero = True
                if not nonzero:
                    continue  # prune: deeper intersections stay empty
                recurse(level + 1, group + [(f, rid)], new_masks)

        base_masks = {}
        for s in shards:
            if filter_call is not None:
                if not isinstance(filter_call, Call):
                    raise ExecutionError("GroupBy filter must be a call")
                base_masks[s] = self._bitmap(idx, filter_call, s)
            else:
                base_masks[s] = self._ones()
        recurse(0, [], base_masks)
        return results

    # ------------------------------------------------------------ writes
    def _execute_write(self, idx: Index, call: Call) -> Any:
        name = call.name
        if name == "Set":
            return self._execute_set(idx, call)
        if name == "Clear":
            return self._execute_clear(idx, call)
        if name == "ClearRow":
            return self._execute_clear_row(idx, call)
        if name == "Store":
            return self._execute_store(idx, call)
        if name == "SetRowAttrs":
            return self._execute_set_row_attrs(idx, call)
        if name == "SetColumnAttrs":
            return self._execute_set_column_attrs(idx, call)
        raise ExecutionError(f"unknown write call {name!r}")

    def _set_args(self, idx: Index, call: Call) -> tuple[int, Field, Any, datetime | None]:
        if not call.pos_args:
            raise ExecutionError(f"{call.name}() needs a column argument")
        col = self._col_id(idx, call.pos_args[0], create=call.name == "Set")
        ts = None
        for extra in call.pos_args[1:]:
            if isinstance(extra, datetime):
                ts = extra
            else:
                raise ExecutionError(f"unexpected argument {extra!r}")
        fa = call.field_arg()
        if fa is None:
            raise ExecutionError(f"{call.name}() needs a field=row argument")
        fname, row = fa
        return col, self._field(idx, fname), row, ts

    def _execute_set(self, idx: Index, call: Call) -> bool:
        col, field, row, ts = self._set_args(idx, call)
        if field.options.field_type == FIELD_INT:
            if not isinstance(row, int) or isinstance(row, bool):
                raise ExecutionError("int field Set() needs an integer value")
            changed = field.set_value(col, row)
        else:
            row_id = self._row_id(field, row, create=True)
            changed = field.set_bit(row_id, col, timestamp=ts)
        idx.mark_columns_exist(np.array([col], dtype=np.uint64))
        return changed

    def _execute_clear(self, idx: Index, call: Call) -> bool:
        col, field, row, _ts = self._set_args(idx, call)
        if field.options.field_type == FIELD_INT:
            return field.clear_value(col)
        row_id = self._row_id(field, row)
        if row_id is None:
            return False
        return field.clear_bit(row_id, col)

    def _execute_clear_row(self, idx: Index, call: Call) -> bool:
        fa = call.field_arg()
        if fa is None:
            raise ExecutionError("ClearRow() needs a field=row argument")
        fname, row = fa
        field = self._field(idx, fname)
        if field.options.field_type in (FIELD_INT,):
            raise ExecutionError("ClearRow() is not supported on int fields")
        row_id = self._row_id(field, row)
        if row_id is None:
            return False
        changed = False
        for view in field.views.values():
            for frag in view.fragments.values():
                changed |= frag.clear_row(row_id)
        return changed

    def _execute_store(self, idx: Index, call: Call) -> bool:
        if len(call.children) != 1:
            raise ExecutionError("Store() takes exactly one row call")
        fa = call.field_arg()
        if fa is None:
            raise ExecutionError("Store() needs a field=row argument")
        fname, row = fa
        field = self._field(idx, fname)
        row_id = self._row_id(field, row, create=True)
        shards = self._shards(idx, None)
        for s in shards:
            words = np.asarray(self._bitmap(idx, call.children[0], s))
            positions = unpack_words(words)
            frag = field.create_view_if_not_exists(
                VIEW_STANDARD
            ).create_fragment_if_not_exists(s)
            frag.set_row(row_id, positions.astype(np.uint64))
        return True

    def _execute_set_row_attrs(self, idx: Index, call: Call) -> None:
        if len(call.pos_args) < 2:
            raise ExecutionError("SetRowAttrs(field, row, attrs...) needs 2 args")
        field = self._field(idx, call.pos_args[0])
        row_id = self._row_id(field, call.pos_args[1], create=True)
        field.row_attrs.set_attrs(row_id, dict(call.args))
        return None

    def _execute_set_column_attrs(self, idx: Index, call: Call) -> None:
        if len(call.pos_args) < 1:
            raise ExecutionError("SetColumnAttrs(col, attrs...) needs a column")
        col = self._col_id(idx, call.pos_args[0], create=True)
        idx.column_attrs.set_attrs(col, dict(call.args))
        return None
