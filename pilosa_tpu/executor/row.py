"""Row query result — a bitmap spanning shards.

Reference: row.go (Row with per-shard segments; cross-shard "union" of
results is concatenation because shards cover disjoint column ranges).
Segments here are packed uint32 words (device or host); materializing
column IDs happens once at the API boundary.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.roaring import unpack_words, words_count
from pilosa_tpu.shardwidth import SHARD_WIDTH


class RowResult:
    """Per-shard packed segments of one logical row / bitmap expression."""

    def __init__(self, segments: dict[int, np.ndarray] | None = None):
        # shard -> uint32[WORDS_PER_SHARD] (jax or numpy array)
        self.segments = segments or {}
        self.attrs: dict = {}
        self.keys: list[str] | None = None
        # Options() wrapper flags (reference: QueryRequest ExcludeColumns/
        # ExcludeRowAttrs; ColumnAttrSets when columnAttrs=true)
        self.exclude_columns = False
        self.exclude_row_attrs = False
        self.column_attr_sets: list[dict] | None = None

    def count(self) -> int:
        return sum(words_count(np.asarray(w)) for w in self.segments.values())

    def columns(self) -> np.ndarray:
        """Absolute column IDs, ascending, uint64."""
        parts = []
        for shard in sorted(self.segments):
            pos = unpack_words(np.asarray(self.segments[shard]))
            if pos.size:
                parts.append(pos.astype(np.uint64) + np.uint64(shard * SHARD_WIDTH))
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    def to_json(self) -> dict:
        out: dict = {"columns": self.columns().tolist()}
        if self.keys is not None:
            out = {"keys": self.keys}
        if self.exclude_columns:
            out.pop("columns", None)
            out.pop("keys", None)
        if self.attrs and not self.exclude_row_attrs:
            out["attrs"] = self.attrs
        return out
