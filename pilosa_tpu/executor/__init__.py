"""L2 query execution (reference: executor.go, row.go)."""

from pilosa_tpu.executor.executor import ExecutionError, Executor, SumCount
from pilosa_tpu.executor.row import RowResult

__all__ = ["Executor", "ExecutionError", "RowResult", "SumCount"]
