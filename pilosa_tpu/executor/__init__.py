"""L2 query execution (reference: executor.go, row.go; the cross-query
wave scheduler is this repo's addition — docs/query-batching.md)."""

from pilosa_tpu.executor.executor import ExecutionError, Executor, SumCount
from pilosa_tpu.executor.row import RowResult
from pilosa_tpu.executor.scheduler import WaveScheduler

__all__ = [
    "Executor",
    "ExecutionError",
    "RowResult",
    "SumCount",
    "WaveScheduler",
]
