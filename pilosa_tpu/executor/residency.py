"""Tiered, compressed device residency (docs/device-residency.md).

Dense [R, S, W] stacks make a field's HBM footprint O(rows) regardless
of how sparse the rows are; once a stack exceeds the device budget the
executor used to fall off a cliff to the dense hot-row slot path or
host routing.  This module is the layout-adaptive middle ground the
Roaring line of work argues for (arXiv 1402.6407 / 1603.06549): each
RESIDENT row of an over-budget field is packed as whichever container
its population actually fits —

- ``dense``  — the packed uint32 words themselves ([S, W] plane);
- ``sparse`` — a sorted int32 list of global bit positions;
- ``run``    — int32 [start, end) intervals of consecutive bits;

— and the device kernels (ops/containers.py) evaluate queries directly
over the compressed payloads.  A hot/cold LRU tier sits under the
StackCache's byte ledger: hot rows stay resident compressed, cold rows
demote to the host (where the cost router already knows how to serve
them), and per-row touch counts re-promote a shifting working set.

The chooser and host-side packers live here (pure numpy — packing runs
on fragment host data); the device stores are orchestrated by
compile.StackCache under its lock.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

# The container taxonomy the device chooser emits.  The analyzer's
# parity rule pins this literal against the host engine's
# ``decode_container`` branches (executor/hostpath.py) and the device
# planner's kind dispatch — a kind without both sides is a routing 500
# waiting for the day the chooser picks it.
CONTAINER_KINDS = {"dense", "sparse", "run"}

# sparse containers cap their id list; rows past this stay dense (the
# id list would approach the plane size anyway)
SPARSE_MAX_IDS = 2048
# run containers cap their interval list; fragmented rows past this
# fall through to sparse/dense
RUN_MAX_INTERVALS = 128
# touches before a cold row is PROMOTED into the resident tier; below
# it the row serves via a one-shot host-packed upload (host-served,
# merged exactly on device) so one-off scans don't churn the LRU
PROMOTE_TOUCHES = 2
# per-entry bound on remembered touch counts (plain LRU of counters)
MAX_TOUCH_ROWS = 8192
# int32 ids bound the flattened plane bit space
_MAX_PLANE_BITS = 1 << 31


def analyze_plane(plane: np.ndarray) -> tuple[int, int]:
    """(n_bits, n_runs) of a packed uint32 plane — O(words), no bit
    unpacking.  Run starts are ``word & ~(word << 1 | carry)`` with the
    carry chaining bit 31 across flattened word boundaries."""
    y = np.ascontiguousarray(plane).reshape(-1)
    nbits = int(np.bitwise_count(y).sum())
    if nbits == 0:
        return 0, 0
    prev = (y << np.uint32(1)) | np.concatenate(
        ([np.uint32(0)], y[:-1] >> np.uint32(31))
    )
    nruns = int(np.bitwise_count(y & ~prev).sum())
    return nbits, nruns


def choose_container(nbits: int, nruns: int, plane_words: int) -> str:
    """Pick the cheapest container for a row with ``nbits`` set bits in
    ``nruns`` runs over a ``plane_words``-word plane.  Costs in uint32
    words: dense = plane_words, sparse = nbits, run = 2·nruns — the
    Roaring rule with the device store caps applied."""
    if plane_words * 32 > _MAX_PLANE_BITS:
        return "dense"  # int32 id space exhausted — see ops/containers.py
    run_cost = 2 * nruns
    if nruns <= RUN_MAX_INTERVALS and run_cost < min(
        plane_words, nbits if nbits else plane_words
    ):
        return "run"
    if nbits <= SPARSE_MAX_IDS and nbits < plane_words:
        return "sparse"
    return "dense"


def pack_container(kind: str, plane: np.ndarray) -> np.ndarray:
    """Pack a [S, W] plane into its container payload (the inverse of
    hostpath.decode_container).  ``dense`` returns the plane itself."""
    if kind == "dense":
        return plane
    bits = np.unpackbits(
        np.ascontiguousarray(plane).reshape(-1).view(np.uint8),
        bitorder="little",
    )
    if kind == "sparse":
        return np.flatnonzero(bits).astype(np.int32)
    if kind == "run":
        edges = np.diff(bits.astype(np.int8))
        starts = np.flatnonzero(edges == 1) + 1
        ends = np.flatnonzero(edges == -1) + 1
        if bits.size and bits[0]:
            starts = np.concatenate(([0], starts))
        if bits.size and bits[-1]:
            ends = np.concatenate((ends, [bits.size]))
        return np.stack([starts, ends], axis=1).astype(np.int32)
    raise ValueError(f"unknown container kind {kind!r}")


def _pow2_floor(n: int) -> int:
    return 1 << (int(n).bit_length() - 1) if n >= 1 else 0


class TieredEntry:
    """Per-(field, view, shards) residency state: one store per
    container kind (a fixed-capacity device array + row→slot LRU) and
    the touch counters driving promotion.  All mutation happens under
    the owning StackCache's lock; the device arrays are immutable
    snapshots (functional scatter updates swap them), so a query that
    captured (array, slots) can never read a reassigned slot."""

    def __init__(self, n_shards: int, budget: int):
        self.versions: tuple | None = None
        self.view_ver = None
        # stores materialize lazily per kind — an all-sparse field never
        # allocates its dense or run store
        self.stores: dict[str, dict] = {}
        self.kinds: OrderedDict[int, str] = OrderedDict()  # chooser memo
        self.touch: OrderedDict[int, int] = OrderedDict()
        self.n_shards = n_shards
        self.budget = budget

    # ------------------------------------------------------- capacities
    def capacity(self, kind: str, plane_words: int) -> tuple[int, int]:
        """(rows, payload_len) a store of ``kind`` holds.  Dense gets
        half the budget (mirroring hot_capacity — a full-budget store
        would thrash against every dense stack); sparse an eighth, runs
        a sixteenth.  Floors keep tiny test budgets functional."""
        if kind == "dense":
            h = (self.budget // 2) // max(1, plane_words * 4)
            return max(8, _pow2_floor(h)), plane_words
        # sparse/run floors cover a full BSI slice block (≤ 66 slice
        # rows) so over-budget int fields can assemble their [D, S, W]
        # block from compressed slices in ONE atomic batch
        if kind == "sparse":
            k = SPARSE_MAX_IDS
            h = (self.budget // 8) // (k * 4)
            return max(128, _pow2_floor(h)), k
        k = RUN_MAX_INTERVALS
        h = (self.budget // 16) // (k * 2 * 4)
        return max(128, _pow2_floor(h)), k

    def note_touch(self, row: int) -> int:
        """Bump and return a row's touch count (bounded LRU)."""
        n = self.touch.pop(row, 0) + 1
        self.touch[row] = n
        while len(self.touch) > MAX_TOUCH_ROWS:
            self.touch.popitem(last=False)
        return n

    def resident(self, row: int, kind: str) -> bool:
        st = self.stores.get(kind)
        return st is not None and row in st["slots"]

    def resident_rows(self) -> int:
        return sum(len(st["slots"]) for st in self.stores.values())

    def drop_rows(self, rows) -> None:
        """Evict specific rows (stale after a write) — slots return to
        the freelist; kind memos invalidate (the write may have changed
        the row's class)."""
        for st in self.stores.values():
            for r in rows:
                slot = st["slots"].pop(r, None)
                if slot is not None:
                    st["free"].append(slot)
        for r in rows:
            self.kinds.pop(r, None)

    def clear(self) -> None:
        for st in self.stores.values():
            st["free"].extend(st["slots"].values())
            st["slots"].clear()
        self.kinds.clear()
