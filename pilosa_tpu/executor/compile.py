"""Query compilation: PQL call tree → ONE jitted device program.

Reference: executor.go walks the AST per shard with Go hot loops and
reduces over HTTP. Here the whole read query becomes a single XLA
program over *stacked* field arrays:

- each (field, view) keeps a device-resident stacked matrix
  ``uint32[R, S, W]`` (R = padded rows, S = shards; row-major so a row
  gather reads one contiguous [S, W] plane — see stack_view_matrices)
  rebuilt only when a fragment version changes — uploads are amortized
  across queries;
- a call tree compiles to a closure over (matrix, row_id) leaf inputs;
  row IDs are traced scalars, so one compiled program serves every row
  of the same query shape (Count(Intersect(Row, Row)) compiles once);
- a shard mask input restricts execution to a query's shard subset
  without recompiling;
- the reduction (Count/Sum/TopN) happens inside the same program, so a
  query is one host→device dispatch and one scalar readback.

The structural cache key is the call tree's shape with row IDs
abstracted out; jax.jit's own shape cache handles S/R/W changes.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from pilosa_tpu import ops
from pilosa_tpu.core import (
    BSI_OFFSET,
    EXISTENCE_FIELD,
    FIELD_INT,
    FIELD_TIME,
    VIEW_BSI,
    VIEW_STANDARD,
    Field,
    Index,
)
from pilosa_tpu.core.timequantum import views_by_time_range
from pilosa_tpu.pql import Call, Condition, coerce_timestamp
from pilosa_tpu.shardwidth import WORDS_PER_SHARD
from pilosa_tpu.utils import saturation


class PlanError(ValueError):
    pass


class StackOverBudget(Exception):
    """A field's dense [R, S, W] stack would exceed the device budget.

    Raised EXPLICITLY instead of letting the allocation OOM (SURVEY §7
    hard part (e)). Callers fall back: Row() leaves go through the
    hot-row slot stack, TopN streams row chunks; anything else surfaces
    the clear error."""

    def __init__(self, field: str, rows: int, bytes_needed: int, budget: int):
        self.field, self.rows = field, rows
        self.bytes_needed, self.budget = bytes_needed, budget
        super().__init__(
            f"field {field!r}: dense stack of {rows} rows needs "
            f"{bytes_needed / 2**20:.0f} MiB on device (budget "
            f"{budget / 2**20:.0f} MiB); high-cardinality fields answer "
            "Row/Count/TopN via the hot-row path"
        )


# --------------------------------------------------------------- stacking
def stack_view_matrices(view, shards: list[int]) -> tuple[np.ndarray, int]:
    """Stack a view's fragment host matrices → (np uint32[R, S, W], R).

    Shared by the query compiler's StackCache and the mesh engine
    (parallel/mesh.py). Reads fragment HOST matrices — no per-fragment
    device round trips; the caller does one upload for the whole stack.

    ROW-MAJOR ([R, S, W], not [S, R, W]) is load-bearing for query
    latency: TPU tiles the two minor dims, so with rows as a middle dim
    a tile spans all R rows of 128 words and gathering ONE row streams
    the ENTIRE stack through the VPU (measured 2026-07-30 at 10.7B
    columns: 29.9 ms/query ≈ whole-stack read at roofline). With rows
    leading, a row gather is a contiguous [S, W] plane — only the rows a
    query touches cross HBM.
    """
    mats, max_rows = [], 1
    for s in shards:
        frag = view.fragment(s) if view else None
        if frag is None:
            mats.append(None)
        else:
            m, _n = frag.host_matrix()
            mats.append(m)
            max_rows = max(max_rows, m.shape[0])
    stacked = np.zeros((max_rows, len(shards), WORDS_PER_SHARD), dtype=np.uint32)
    from pilosa_tpu import native

    if not native.stack_fill(mats, stacked):
        # numpy fallback — rows outer, shards inner: destination writes
        # land contiguously in each [S, W] row plane. Controlled A/B at
        # 10 GiB on the bench host (fresh destinations, alternating
        # reps): shard-inner strided fill 44.2/23.7 s vs this order
        # 20.2/11.7 s — consistently ~2× faster. The C path above
        # parallelizes the same row-plane order across threads (ctypes
        # releases the GIL), cutting the pod-scale stack build further.
        for r in range(max_rows):
            plane = stacked[r]
            for i, m in enumerate(mats):
                if m is not None and r < m.shape[0]:
                    plane[i] = m[r]
    return stacked, max_rows


# scatter index sentinel: out of bounds on any axis ⇒ mode="drop" skips it
_OOB = np.int32(2**30)

_budget_cache: list[int] = []
# explicit override installed from config (device-stack-budget-bytes);
# wins over the env var and the HBM probe
_budget_override: list[int] = []


def set_stack_budget(n: int | None) -> None:
    """Install the configured device stack budget (Config field
    ``device-stack-budget-bytes``; the server wires it at boot).  None
    or 0 clears back to env/HBM resolution.  Always resets the memo so
    tests and re-configuration see the change immediately."""
    _budget_override.clear()
    if n:
        _budget_override.append(int(n))
    _budget_cache.clear()


def reset_stack_budget_cache() -> None:
    """Drop the memoized resolution (tests re-resolve after changing
    PILOSA_TPU_STACK_BUDGET; the old cache was append-only)."""
    _budget_cache.clear()


def stack_budget_if_resolved() -> int | None:
    """The budget WITHOUT triggering resolution, or None while only the
    HBM path (which initializes the JAX backend) could answer.  The
    /debug/resources ledger reads through this: a control-plane scrape
    during the device-probe window must never be the first jax call in
    the process — that hang is exactly what the probe gate exists to
    prevent, and debug routes do not pass through the gate."""
    if _budget_override:
        return _budget_override[0]
    if _budget_cache:
        return _budget_cache[0]
    env = os.environ.get("PILOSA_TPU_STACK_BUDGET")
    return int(env) if env else None


def _stack_budget() -> int:
    """See StackCache.STACK_BYTES_BUDGET. Cached after first resolution
    (device memory limits don't change mid-process)."""
    # override → cache → env, shared with the non-initializing ledger
    # accessor so /debug/resources and the enforced budget cannot drift
    resolved = stack_budget_if_resolved()
    if resolved is not None:
        if not _budget_cache and not _budget_override:
            _budget_cache.append(resolved)  # env path: memoize like HBM
        return resolved
    budget = 0
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        # 70% of reported HBM even when that is below 2 GiB — the
        # headroom matters more on small devices, not less
        budget = int(int(stats.get("bytes_limit", 0)) * 0.7)
    except Exception:  # pilosa: allow(broad-except) — memory_stats
        # is backend-specific and raises backend-specific errors
        pass  # backend without memory stats (e.g. CPU)
    if budget <= 0:
        budget = 2 << 30
    _budget_cache.append(budget)
    return budget


@jax.jit
def _scatter_rows(store, idx, rows):
    """Functional row scatter for the tiered container stores:
    ``store[idx[k]] = rows[k]`` for dense [H,S,W], sparse [H,K] and run
    [H,K,2] stores alike. _OOB padding indices drop. Not donated — a
    query snapshot may still hold the previous array."""
    return store.at[idx].set(rows, mode="drop")


@jax.jit
def _apply_stack_delta(matrix, idx, rows):
    """Scatter ``rows[k]`` into ``matrix[idx[k,0], idx[k,1]]`` on device
    (row-major stacks: idx columns are (row, shard)). Padding entries use
    the _OOB sentinel and are dropped.
    Deliberately NOT donated: concurrent readers may still hold the old
    stack; the device-to-device copy rides HBM bandwidth, which is the
    point — the host→device upload is what O(dirty rows) avoids."""
    return matrix.at[idx[:, 0], idx[:, 1]].set(rows, mode="drop")


class StackCache:
    """Device-resident stacked (field, view) matrices.

    Entries key on the exact shard list and invalidate via per-fragment
    (uid, version) tokens — a deleted-and-recreated index gets fresh
    fragment uids, so stale data can never be served. An LRU cap bounds
    device memory when workloads query many distinct shard subsets.

    Point writes between queries take the DELTA path: the fragments'
    dirty-row history yields the changed (shard, row) set, only those
    packed rows cross host→device, and a scatter updates the resident
    stack in place of a full O(S·R·W) re-upload (VERDICT r1 item 4;
    reference analogue: fragment.go bulkImport's incremental discipline).
    """

    MAX_ENTRIES = 64
    MAX_DELTA_ROWS = 1024  # beyond this a full restack is cheaper

    # device-bytes cap for any one dense stack; larger fields take the
    # hot-row path. Resolution order: PILOSA_TPU_STACK_BUDGET env →
    # 70% of the device's reported HBM limit (a 16 GiB chip serves a
    # 10 GiB pod-scale stack out of the box) → 2 GiB. Lazy so importing
    # the module never initializes a backend; tests monkeypatch the
    # class attribute with a plain int, which shadows the property.
    @property
    def STACK_BYTES_BUDGET(self) -> int:  # noqa: N802 — historical name
        return _stack_budget()

    # How over-budget fields serve resident rows (docs/device-residency.md):
    # "tiered"  — per-row compressed containers (dense/sparse/run) with a
    #             hot/cold LRU tier and touch-driven promotion (default);
    # "slots"   — the legacy dense hot-row slot stack (tests pin it to
    #             exercise that path; no compression, no cold tier).
    RESIDENCY_MODE = "tiered"
    MAX_TIERED_ENTRIES = 4  # count cap; the byte ledger is the real bound

    def __init__(self, mesh_ctx=None, stats=None):
        from collections import OrderedDict

        self._cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._hot: "OrderedDict[tuple, dict]" = OrderedDict()
        # tiered compressed residency entries (executor/residency.py)
        self._tiered: "OrderedDict[tuple, Any]" = OrderedDict()
        self.mesh_ctx = mesh_ctx  # parallel.mesh.MeshContext | None
        self.stats = stats  # optional StatsClient for residency metrics
        # contention-counted (docs/profiling.md): /debug/saturation's
        # "stack-cache" lock family — every stack build/eviction and
        # route-time token check serializes here
        self._lock = saturation.ContendedLock("stack-cache")
        # shared byte ledger across BOTH caches: the budget is an
        # AGGREGATE resident cap, not just per-stack — a per-entry check
        # alone would let two near-budget stacks coexist and OOM the
        # device once the budget scales to 70% of HBM
        self._bytes: dict[tuple, int] = {}
        # projected bytes of builds in flight (admitted, not yet
        # installed): two concurrent builders of different keys must see
        # each other's claims or they co-allocate past the budget.
        # Keyed by a PER-BUILD token, not the stack key — two concurrent
        # builds of the SAME key must each hold a claim, or the first to
        # finish releases the second's bytes while it is still allocating
        self._reserved: dict[object, int] = {}
        self.resident_bytes = 0
        # observability: tests assert the write path stays incremental
        self.full_restacks = 0
        self.delta_updates = 0
        self.delta_rows_uploaded = 0
        self.hot_row_uploads = 0
        # tiered residency counters (satellite: eviction/tier
        # observability; /debug/vars deviceResidency reads these)
        self.rows_promoted = 0
        self.rows_demoted = 0
        self.cold_uploads = 0
        self.evictions = {"dense": 0, "hot": 0, "tiered": 0}
        self._container_bytes = {"dense": 0, "sparse": 0, "run": 0}

    # ----------------------------------------------------- byte ledger
    # callers hold self._lock
    def _account(self, key: tuple, nbytes: int) -> None:
        self.resident_bytes += nbytes - self._bytes.get(key, 0)
        self._bytes[key] = nbytes

    def _forget(self, key: tuple) -> None:
        self.resident_bytes -= self._bytes.pop(key, 0)

    def _evict_for(self, need: int, keep: tuple | None = None) -> None:
        """Evict LRU entries (dense stacks first, then hot slot stacks,
        then tiered container entries) until ``need`` more bytes fit
        under the budget. The entry being (re)built is exempt; if
        nothing evictable remains the admit proceeds anyway — the
        per-stack check already bounds any single entry."""
        budget = self.STACK_BYTES_BUDGET
        while (
            self.resident_bytes + sum(self._reserved.values()) + need > budget
        ):
            victim = next((k for k in self._cache if k != keep), None)
            if victim is not None:
                del self._cache[victim]
                self._forget(victim)
                self._note_eviction("dense")
                continue
            victim = next((k for k in self._hot if k != keep), None)
            if victim is not None:
                del self._hot[victim]
                self._forget(victim)
                self._note_eviction("hot")
                continue
            victim = next((k for k in self._tiered if k != keep), None)
            if victim is None:
                break
            self._forget_tiered(victim, self._tiered.pop(victim))
            self._note_eviction("tiered")

    def _note_eviction(self, tier: str) -> None:
        # caller holds self._lock
        self.evictions[tier] = self.evictions.get(tier, 0) + 1
        if self.stats is not None:
            self.stats.count("stack_evictions_total", tags={"tier": tier})

    @staticmethod
    def _projected_rows(view, shards: list[int]) -> int:
        """Padded stack height WITHOUT materializing any host matrix —
        the over-budget check must not itself allocate O(R·W)."""
        from pilosa_tpu.core.fragment import _pad_rows

        n = 1
        for s in shards:
            frag = view.fragment(s) if view else None
            if frag is not None:
                n = max(n, frag.n_rows())
        return _pad_rows(n)

    def matrix(self, idx: Index, field: Field, view_name: str, shards: list[int]):
        """(jnp uint32[R, S, W], n_rows int) for the given shard list.

        Raises StackOverBudget when the dense stack would exceed
        STACK_BYTES_BUDGET — callers use hot_slot()/hot_dev() or chunked
        scans instead."""
        view = field.view(view_name)
        key = (idx.name, field.name, view_name, tuple(shards))
        # whole-view mutation stamp read BEFORE the per-fragment tokens:
        # a mutation racing this read advances view.version, so an entry
        # stamped with the earlier value just re-validates next query
        view_ver = view.version if view is not None else None
        with self._lock:
            cached = self._cache.get(key)
            if (
                cached is not None
                and view_ver is not None
                and cached[3] == view_ver
            ):
                # O(1) fast path — no mutation anywhere in the view since
                # this entry was stamped, so BOTH O(S) scans (budget
                # projection + per-fragment tokens; 10k+ calls per leaf
                # per query at pod scale) are skipped. Over-budget fields
                # never enter the cache, so a hit implies within-budget.
                self._cache.move_to_end(key)
                return cached[1], cached[2]
        r_pad = self._projected_rows(view, shards)
        need = len(shards) * r_pad * WORDS_PER_SHARD * 4
        if need > self.STACK_BYTES_BUDGET:
            raise StackOverBudget(
                field.name, r_pad, need, self.STACK_BYTES_BUDGET
            )
        with self._lock:
            # evict for the PROJECTED bytes BEFORE the build allocates on
            # device — evicting only at install would let the new stack
            # coexist with victims at ~2× budget peak (a same-key rebuild
            # still transiently holds old+new; concurrent readers may use
            # the old array, so it cannot be dropped early)
            self._evict_for(need - self._bytes.get(key, 0), keep=key)
            cached = self._cache.get(key)
            versions = tuple(self._frag_token(view, s) for s in shards)
            if cached is not None and cached[0] == versions:
                self._cache[key] = (versions, cached[1], cached[2], view_ver)
                self._cache.move_to_end(key)
                return cached[1], cached[2]
            # reserve the projection so a concurrent admit of a DIFFERENT
            # key can't also pass eviction and co-allocate past the
            # budget while both builds are in flight (ADVICE r3)
            build_token = object()
            self._reserved[build_token] = need
        # build OUTSIDE the lock: a slow restack/upload must not convoy
        # concurrent cache-hit readers. A racing write between the version
        # snapshot and the build just means the next query sees another
        # version mismatch and applies the remainder (delta application is
        # idempotent — rows carry full contents).
        try:
            entry = None
            if cached is not None:
                entry = self._try_delta(cached, view, shards, versions, view_ver)
            if entry is None:
                stacked, max_rows = stack_view_matrices(view, shards)
                if self.mesh_ctx is not None:
                    dev = self.mesh_ctx.place_stack(stacked)
                else:
                    dev = jnp.asarray(stacked)
                with self._lock:
                    self.full_restacks += 1
                entry = (versions, dev, max_rows, view_ver)
        except BaseException:
            with self._lock:
                self._reserved.pop(build_token, None)
            raise
        with self._lock:
            self._reserved.pop(build_token, None)
            # last-writer-wins install is self-healing: if a concurrent
            # builder installed a different entry, the next call re-reads
            # fragment versions and reconciles via the delta path
            nbytes = int(entry[1].nbytes)
            self._evict_for(nbytes - self._bytes.get(key, 0), keep=key)
            self._cache[key] = entry
            self._account(key, nbytes)
            self._cache.move_to_end(key)
            while len(self._cache) > self.MAX_ENTRIES:
                victim, _ = self._cache.popitem(last=False)
                self._forget(victim)
            return entry[1], entry[2]

    def _try_delta(self, cached, view, shards: list[int], versions: tuple, view_ver):
        """Apply changed fragments' dirty rows to the cached device stack;
        None ⇒ fall back to a full restack (unknown history, fragment
        replaced, row growth past the stack height, or too many rows)."""
        old_versions, dev, max_rows = cached[0], cached[1], cached[2]
        updates: list[tuple[int, int, np.ndarray]] = []
        for i, s in enumerate(shards):
            old_uid, old_ver = old_versions[i]
            new_uid, _new_ver = versions[i]
            if (old_uid, old_ver) == versions[i]:
                continue
            if old_uid != new_uid:
                return None  # fragment created or replaced under the key
            frag = view.fragment(s)
            if frag is None:
                return None
            dirty = frag.dirty_rows_since(old_ver)
            if dirty is None:
                return None
            if len(updates) + len(dirty) > self.MAX_DELTA_ROWS:
                return None
            host_m, _n = frag.host_matrix()
            if host_m.shape[0] > max_rows:
                return None  # stack needs to grow — restack
            for r in sorted(dirty):
                if r >= max_rows:
                    return None
                words = (
                    host_m[r]
                    if r < host_m.shape[0]
                    else np.zeros(WORDS_PER_SHARD, dtype=np.uint32)
                )
                updates.append((i, r, words))
        if not updates:
            return (versions, dev, max_rows, view_ver)
        k_pad = 1 << (len(updates) - 1).bit_length()
        idx_arr = np.full((k_pad, 2), _OOB, dtype=np.int32)  # OOB ⇒ drop
        row_arr = np.zeros((k_pad, WORDS_PER_SHARD), dtype=np.uint32)
        for k, (i, r, words) in enumerate(updates):
            idx_arr[k] = (r, i)
            row_arr[k] = words
        new_dev = _apply_stack_delta(dev, idx_arr, row_arr)
        if new_dev.sharding != dev.sharding:
            # the scatter must not silently demote the stack's SPMD layout
            new_dev = jax.device_put(new_dev, dev.sharding)
        with self._lock:
            self.delta_updates += 1
            self.delta_rows_uploaded += len(updates)
        return (versions, new_dev, max_rows, view_ver)

    @staticmethod
    def _frag_token(view, shard: int) -> tuple:
        frag = view.fragment(shard) if view else None
        return (-1, -1) if frag is None else (frag.uid, frag.version)

    def stats_snapshot(self) -> dict:
        """Counter view for /debug/vars (owns the field names so
        transport code never reads cache internals); increments happen
        under the same lock, so no update is lost."""
        with self._lock:
            return {
                "fullRestacks": self.full_restacks,
                "deltaUpdates": self.delta_updates,
                "deltaRowsUploaded": self.delta_rows_uploaded,
                "hotRowUploads": self.hot_row_uploads,
                "entries": len(self._cache),
                "hotEntries": len(self._hot),
                "tieredEntries": len(self._tiered),
                "residentBytes": self.resident_bytes,
                "budgetBytes": self.STACK_BYTES_BUDGET,
            }

    def invalidate(self) -> None:
        with self._lock:
            self._bytes.clear()
            self.resident_bytes = 0
            self._cache.clear()
            self._hot.clear()
            self._tiered.clear()
            self._container_bytes = {"dense": 0, "sparse": 0, "run": 0}
            self._push_residency_gauges()

    # ----------------------------------------------------- hot-row stacks
    # High-cardinality fields (dense stack over STACK_BYTES_BUDGET) keep
    # only an LRU working set of rows on device: an [H, S, W] slot stack
    # plus a row→slot map. Cold rows live in the host roaring bitmaps and
    # are promoted on first touch with an O(S·W) scatter — never a full
    # host matrix (SURVEY §7 hard part (e)).

    def hot_capacity(self, n_shards: int) -> int:
        # HALF the aggregate budget: a full-budget slot stack would be
        # mutually exclusive with every dense stack, and a hybrid query
        # (dense field ∩ hot field) would evict one to admit the other
        # on every request — permanent restack/re-promotion thrash
        h = (self.STACK_BYTES_BUDGET // 2) // max(
            1, n_shards * WORDS_PER_SHARD * 4
        )
        return max(8, 1 << (int(h).bit_length() - 1)) if h >= 8 else 8

    MAX_HOT_ENTRIES = 4  # count cap; the byte ledger is the real bound

    def _hot_entry(self, idx: Index, field: Field, view_name: str, shards):
        view = field.view(view_name)
        key = ("hot", idx.name, field.name, view_name, tuple(shards))
        # same O(1) whole-view fast path as matrix(): stamp read before
        # tokens, so a racing mutation only costs a re-validation
        view_ver = view.version if view is not None else None
        entry = self._hot.get(key)
        h = self.hot_capacity(len(shards))
        if (
            entry is not None
            and entry["h"] == h
            and view_ver is not None
            and entry.get("view_ver") == view_ver
        ):
            self._hot.move_to_end(key)
            return entry, view
        versions = tuple(self._frag_token(view, s) for s in shards)
        if entry is None or entry["h"] != h:
            from collections import OrderedDict

            zeros = np.zeros((h, len(shards), WORDS_PER_SHARD), dtype=np.uint32)
            self._evict_for(int(zeros.nbytes) - self._bytes.get(key, 0), keep=key)
            dev = (
                self.mesh_ctx.place_stack(zeros)
                if self.mesh_ctx is not None
                else jnp.asarray(zeros)
            )
            entry = {
                "versions": versions,
                "dev": dev,
                "slots": OrderedDict(),
                "h": h,
                "view_ver": view_ver,
            }
            self._hot[key] = entry
            self._account(key, int(zeros.nbytes))
            self._hot.move_to_end(key)
            while len(self._hot) > self.MAX_HOT_ENTRIES:
                victim, _ = self._hot.popitem(last=False)
                self._forget(victim)
            return entry, view
        self._hot.move_to_end(key)
        if entry["versions"] != versions:
            # reconcile resident rows against fragment mutations
            stale: set[int] | None = set()
            for i, s in enumerate(shards):
                old_uid, old_ver = entry["versions"][i]
                new_uid, new_ver = versions[i]
                if (old_uid, old_ver) == (new_uid, new_ver):
                    continue
                frag = view.fragment(s) if view else None
                if frag is None or old_uid != new_uid:
                    stale = None
                    break
                dirty = frag.dirty_rows_since(old_ver)
                if dirty is None:
                    stale = None
                    break
                stale |= dirty
            if stale is None:
                entry["slots"].clear()
            else:
                self._upload_hot_rows(
                    entry,
                    view,
                    shards,
                    [(r, entry["slots"][r]) for r in stale & set(entry["slots"])],
                )
            entry["versions"] = versions
        entry["view_ver"] = view_ver
        return entry, view

    def _upload_hot_rows(self, entry, view, shards, pairs: list[tuple[int, int]]):
        """One batched scatter for every (row_id, slot) pair — the slot
        stack is full-copied per scatter, so k rows must cost one copy,
        not k."""
        if not pairs:
            return
        n_s = len(shards)
        k = len(pairs)
        data = np.zeros((k * n_s, WORDS_PER_SHARD), dtype=np.uint32)
        idx_arr = np.empty((k * n_s, 2), dtype=np.int32)
        for j, (row_id, slot) in enumerate(pairs):
            for i, s in enumerate(shards):
                frag = view.fragment(s) if view else None
                if frag is not None:
                    data[j * n_s + i] = frag.row_packed(row_id)
                idx_arr[j * n_s + i] = (slot, i)
        new_dev = _apply_stack_delta(entry["dev"], idx_arr, data)
        if new_dev.sharding != entry["dev"].sharding:
            new_dev = jax.device_put(new_dev, entry["dev"].sharding)
        entry["dev"] = new_dev
        # no lock acquisition: every caller (hot_batch → _hot_entry →
        # here) already holds self._lock, which is non-reentrant
        self.hot_row_uploads += len(pairs)

    def hot_batch(
        self,
        idx: Index,
        field: Field,
        view_name: str,
        shards: list[int],
        row_ids: list[int],
    ):
        """Atomically ensure EVERY row in ``row_ids`` is device-resident
        and return ``(dev [H,S,W], {row_id: slot})`` captured in one
        critical section. The returned array object is immutable — later
        evictions by other queries scatter into a NEW array, so a
        program compiled against this (dev, slots) pair can never read a
        reassigned slot (code-review r2: plan-time slots must not go
        stale before dispatch)."""
        with self._lock:
            entry, view = self._hot_entry(idx, field, view_name, shards)
            slots = entry["slots"]
            need = [r for r in dict.fromkeys(row_ids) if r >= 0]
            if len(need) > entry["h"]:
                raise StackOverBudget(
                    field.name,
                    len(need),
                    len(need) * len(shards) * WORDS_PER_SHARD * 4,
                    self.STACK_BYTES_BUDGET,
                )
            # bump every needed resident row first so the LRU never
            # evicts one member of this batch to admit another
            for r in need:
                if r in slots:
                    slots.move_to_end(r)
            uploads: list[tuple[int, int]] = []
            for r in need:
                if r in slots:
                    continue
                if len(slots) < entry["h"]:
                    slot = len(slots)
                else:
                    _evicted, slot = slots.popitem(last=False)
                slots[r] = slot
                uploads.append((r, slot))
            self._upload_hot_rows(entry, view, shards, uploads)
            return entry["dev"], {r: slots[r] for r in need}

    # ------------------------------------------- tiered compressed residency
    # Over-budget fields in "tiered" mode keep a hot working set of rows
    # resident in per-row COMPRESSED containers — dense words, sparse
    # column ids, or run intervals (executor/residency.py chooses per
    # row; ops/containers.py evaluates directly over the payloads).
    # Cold rows live in the host roaring bitmaps: their first touch
    # serves via a one-shot host-packed upload (host-served, merged
    # exactly on device), repeated touches promote them into residency,
    # and LRU slot reuse demotes the coldest resident row back to host.

    def residency_mode(self) -> str:
        # multi-host meshes serve over-budget fields through the legacy
        # slot path: container payloads are packed from PROCESS-LOCAL
        # fragments in local-position space, which cannot be declared a
        # replicated global array (each process would hold different
        # bits) — the [H, S, W] slot stack, by contrast, shards along S
        # like every other stack
        if self.mesh_ctx is not None and getattr(
            self.mesh_ctx, "multihost", False
        ):
            return "slots"
        return self.RESIDENCY_MODE

    def is_over_budget(
        self, idx: Index, field: Field, view_name: str, shards: list[int]
    ) -> bool:
        """Would this field's dense stack exceed the budget (i.e. do its
        rows serve through the tiered/hot layer)?  O(S) metadata scan,
        no allocation — the router's residency probe."""
        view = field.view(view_name)
        r_pad = self._projected_rows(view, shards)
        need = len(shards) * r_pad * WORDS_PER_SHARD * 4
        return need > self.STACK_BYTES_BUDGET

    def _pack_plane(self, view, shards: list[int], row_id) -> np.ndarray:
        """Host-packed [S, W] plane of one row, straight from fragments."""
        out = np.zeros((len(shards), WORDS_PER_SHARD), dtype=np.uint32)
        if view is None or row_id is None or row_id < 0:
            return out
        for i, s in enumerate(shards):
            frag = view.fragment(s)
            if frag is not None:
                out[i] = frag.row_packed(row_id)
        return out

    def _tiered_entry(self, idx: Index, field: Field, view_name: str, shards):
        """(key, entry, view), versions reconciled. Caller holds _lock.
        Stale resident rows are DROPPED (not re-uploaded): a write may
        change a row's container class, so the next touch re-chooses and
        re-packs; touch counts survive, so a hot row re-promotes on its
        very next query."""
        from pilosa_tpu.executor.residency import TieredEntry

        view = field.view(view_name)
        key = ("tier", idx.name, field.name, view_name, tuple(shards))
        view_ver = view.version if view is not None else None
        entry = self._tiered.get(key)
        if entry is None:
            entry = TieredEntry(len(shards), self.STACK_BYTES_BUDGET)
            self._tiered[key] = entry
            while len(self._tiered) > self.MAX_TIERED_ENTRIES:
                victim = next(k for k in self._tiered if k != key)
                self._forget_tiered(victim, self._tiered.pop(victim))
                self._note_eviction("tiered")
        # track the live budget: set_stack_budget() reconfiguration must
        # size NEW stores from the current value (existing stores keep
        # their allocation — the shared ledger evicts them under
        # pressure like anything else)
        entry.budget = self.STACK_BYTES_BUDGET
        self._tiered.move_to_end(key)
        if view_ver is not None and entry.view_ver == view_ver:
            return key, entry, view
        versions = tuple(self._frag_token(view, s) for s in shards)
        if entry.versions != versions:
            stale: set[int] | None = set()
            if entry.versions is not None:
                for i, s in enumerate(shards):
                    old_uid, old_ver = entry.versions[i]
                    new_uid, _nv = versions[i]
                    if (old_uid, old_ver) == versions[i]:
                        continue
                    frag = view.fragment(s) if view else None
                    if frag is None or old_uid != new_uid:
                        stale = None
                        break
                    dirty = frag.dirty_rows_since(old_ver)
                    if dirty is None:
                        stale = None
                        break
                    stale |= dirty
            else:
                stale = None
            if stale is None:
                entry.clear()
            else:
                rows_dropped = [
                    r
                    for r in stale
                    if any(r in st["slots"] for st in entry.stores.values())
                ]
                self.rows_demoted += len(rows_dropped)
                entry.drop_rows(stale)
            entry.versions = versions
        entry.view_ver = view_ver
        return key, entry, view

    def _tiered_store(self, entry, kind: str, key: tuple) -> dict:
        """Get-or-create one kind's fixed-capacity device store. Caller
        holds _lock; creation charges the byte ledger (evicting LRU
        entries first) and the per-container gauges."""
        from pilosa_tpu.executor.residency import RUN_MAX_INTERVALS, SPARSE_MAX_IDS

        st = entry.stores.get(kind)
        if st is not None:
            return st
        h, _k = entry.capacity(kind, entry.n_shards * WORDS_PER_SHARD)
        if kind == "dense":
            host = np.zeros(
                (h, entry.n_shards, WORDS_PER_SHARD), dtype=np.uint32
            )
        elif kind == "sparse":
            host = np.full((h, SPARSE_MAX_IDS), -1, dtype=np.int32)
        elif kind == "run":
            host = np.zeros((h, RUN_MAX_INTERVALS, 2), dtype=np.int32)
        else:
            raise ValueError(f"unknown container kind {kind!r}")
        nbytes = int(host.nbytes)
        self._evict_for(nbytes, keep=key)
        if self.mesh_ctx is not None:
            dev = (
                self.mesh_ctx.place_stack(host)
                if kind == "dense"
                else self.mesh_ctx.place_block(host)
            )
        else:
            dev = jnp.asarray(host)
        from collections import OrderedDict

        st = {
            "dev": dev,
            "slots": OrderedDict(),
            "free": [],
            "alloc": 0,
            "h": h,
            "nbytes": nbytes,
        }
        entry.stores[kind] = st
        self._account(key, self._bytes.get(key, 0) + nbytes)
        self._container_bytes[kind] += nbytes
        self._push_residency_gauges()
        return st

    def _forget_tiered(self, key: tuple, entry) -> None:
        # caller holds self._lock
        for kind, st in entry.stores.items():
            self._container_bytes[kind] -= st["nbytes"]
        self._forget(key)
        self._push_residency_gauges()

    def _push_residency_gauges(self) -> None:
        if self.stats is None:
            return
        for kind, v in self._container_bytes.items():
            self.stats.gauge(
                "residency_bytes", v, tags={"container": kind}
            )

    def tiered_plan(
        self,
        idx: Index,
        field: Field,
        view_name: str,
        shards: list[int],
        row_id: int,
    ) -> tuple[str, str]:
        """Plan-time residency decision for one row leaf →
        ``(container_kind, action)`` with action one of:

        - "resident" — already on device; the batch snapshot will bump it;
        - "promote"  — touch count reached the threshold; the batch will
          pack + upload it into its container store (rows_promoted);
        - "cold"     — below the threshold; serve via a one-shot
          host-packed plane upload, no residency churn.

        The chooser memoizes per (row, fragment versions); a miss costs
        one host row pack + an O(words) popcount scan."""
        from pilosa_tpu.executor import residency

        with self._lock:
            key, entry, view = self._tiered_entry(idx, field, view_name, shards)
            if row_id is None or row_id < 0:
                return "sparse", "cold"  # unknown key ⇒ all-zero plane
            kind = entry.kinds.get(row_id)
            if kind is not None:
                # LRU, not FIFO: without the bump, a constantly-queried
                # resident row's kind memo would age out behind one-shot
                # cold rows, making tiered_resident report it cold (and
                # re-analyzing its whole plane under the lock each plan)
                entry.kinds.move_to_end(row_id)
            else:
                plane = self._pack_plane(view, shards, row_id)
                nbits, nruns = residency.analyze_plane(plane)
                kind = residency.choose_container(
                    nbits, nruns, len(shards) * WORDS_PER_SHARD
                )
                entry.kinds[row_id] = kind
                while len(entry.kinds) > residency.MAX_TOUCH_ROWS:
                    entry.kinds.popitem(last=False)
            touches = entry.note_touch(row_id)
            if entry.resident(row_id, kind):
                return kind, "resident"
            if touches >= residency.PROMOTE_TOUCHES:
                return kind, "promote"
            return kind, "cold"

    def cold_plane(
        self, idx: Index, field: Field, view_name: str, shards, row_id: int
    ):
        """One-shot device upload of a host-packed row plane — the
        pre-promotion cold service (the host serves the row, the device
        program merges it exactly with resident-compressed rows)."""
        view = field.view(view_name)
        plane = self._pack_plane(view, shards, row_id)
        with self._lock:
            self.cold_uploads += 1
        if self.mesh_ctx is not None:
            return self.mesh_ctx.place_rows(plane)
        return jnp.asarray(plane)

    def tiered_batch(
        self,
        idx: Index,
        field: Field,
        view_name: str,
        shards: list[int],
        needs: "list[tuple[int, str]]",
    ):
        """Atomically ensure every (row, kind) pair is resident and
        return ``({kind: dev_store}, {row: slot})`` captured in one
        critical section — the same immutable-snapshot contract as
        hot_batch (functional scatters swap arrays, so a compiled
        program can never read a reassigned slot)."""
        from pilosa_tpu.executor import residency

        with self._lock:
            key, entry, view = self._tiered_entry(idx, field, view_name, shards)
            uniq = list(dict.fromkeys((r, k) for r, k in needs if r >= 0))
            by_kind: dict[str, list[int]] = {}
            for r, k in uniq:
                by_kind.setdefault(k, []).append(r)
            stores = {
                k: self._tiered_store(entry, k, key) for k in by_kind
            }
            for k, rows in by_kind.items():
                if len(rows) > stores[k]["h"]:
                    # atomic-batch contract: a query needing more rows of
                    # one container kind than its store holds fails
                    # EXPLICITLY — never a silently evicted slot mid-query
                    raise StackOverBudget(
                        f"{field.name} ({k} container store, "
                        f"{stores[k]['h']} slots)",
                        len(rows),
                        len(rows) * len(shards) * WORDS_PER_SHARD * 4,
                        self.STACK_BYTES_BUDGET,
                    )
            # bump resident batch members first so LRU reuse never
            # demotes one member of this batch to admit another
            for k, rows in by_kind.items():
                slots = stores[k]["slots"]
                for r in rows:
                    if r in slots:
                        slots.move_to_end(r)
            slot_map: dict[int, int] = {}
            for k, rows in by_kind.items():
                st = stores[k]
                missing = [r for r in rows if r not in st["slots"]]
                for r in rows:
                    if r in st["slots"]:
                        slot_map[r] = st["slots"][r]
                # pack + validate BEFORE any slot mutation: a payload
                # that no longer fits its planned kind (a racing write
                # changed the row's class) must fail with the slot maps
                # untouched, or later queries would read the assigned
                # but never-written slot as resident zeros
                payloads = {
                    r: self._pack_payload(k, st, view, shards, r)
                    for r in missing
                }
                uploads: list[tuple[int, int]] = []
                for r in missing:
                    if st["free"]:
                        slot = st["free"].pop()
                    elif st["alloc"] < st["h"]:
                        slot = st["alloc"]
                        st["alloc"] += 1
                    else:
                        demoted, slot = st["slots"].popitem(last=False)
                        entry.kinds.pop(demoted, None)
                        self.rows_demoted += 1
                        if self.stats is not None:
                            self.stats.count("rows_demoted")
                    st["slots"][r] = slot
                    slot_map[r] = slot
                    uploads.append((r, slot))
                if uploads:
                    self._upload_tiered_rows(st, k, payloads, uploads)
                    self.rows_promoted += len(uploads)
                    self.hot_row_uploads += len(uploads)
                    if self.stats is not None:
                        self.stats.count("rows_promoted", len(uploads))
            return {k: st["dev"] for k, st in stores.items()}, slot_map

    def _pack_payload(self, kind: str, st: dict, view, shards, row_id: int):
        """Pack one row for its planned container store, validating the
        fit (caller holds _lock and has not yet assigned a slot)."""
        from pilosa_tpu.executor import residency

        payload = residency.pack_container(
            kind, self._pack_plane(view, shards, row_id)
        )
        if kind != "dense" and payload.shape[0] > st["dev"].shape[1]:
            raise StackOverBudget(
                f"row {row_id} no longer fits its planned {kind!r} "
                "container (changed class mid-plan)",
                1,
                int(payload.nbytes),
                self.STACK_BYTES_BUDGET,
            )
        return payload

    def _upload_tiered_rows(
        self, st: dict, kind: str, payloads: dict, uploads
    ) -> None:
        """Scatter pre-packed, pre-validated payloads into one kind
        store (one functional scatter per batch, padded to pow2 so XLA
        retraces stay rare). Caller holds _lock."""
        k_pad = 1 << (len(uploads) - 1).bit_length()
        idx_arr = np.full(k_pad, _OOB, dtype=np.int32)
        rows_arr = np.zeros((k_pad,) + st["dev"].shape[1:], st["dev"].dtype)
        if kind == "sparse":
            rows_arr[:] = -1
        for j, (row_id, slot) in enumerate(uploads):
            payload = payloads[row_id]
            if kind == "dense":
                rows_arr[j] = payload
            else:
                rows_arr[j, : payload.shape[0]] = payload
            idx_arr[j] = slot
        new_dev = _scatter_rows(st["dev"], idx_arr, rows_arr)
        if new_dev.sharding != st["dev"].sharding:
            new_dev = jax.device_put(new_dev, st["dev"].sharding)
        st["dev"] = new_dev

    def tiered_resident(
        self, idx: Index, field: Field, view_name: str, shards, row_id: int
    ) -> bool:
        """Cheap residency probe (router cost model) — never creates
        entries, packs planes, or bumps touch counts."""
        key = ("tier", idx.name, field.name, view_name, tuple(shards))
        with self._lock:
            entry = self._tiered.get(key)
            if entry is None:
                return False
            kind = entry.kinds.get(row_id)
            if kind is None:
                return False
            return entry.resident(row_id, kind)

    def residency_snapshot(self) -> dict:
        """/debug/vars ``deviceResidency`` section + the ?profile=true
        residency block (owns the field names, like stats_snapshot)."""
        with self._lock:
            per_entry = []
            for key, entry in self._tiered.items():
                per_entry.append(
                    {
                        "field": key[2],
                        "view": key[3],
                        "shards": len(key[4]),
                        "rows": {
                            k: len(st["slots"])
                            for k, st in entry.stores.items()
                        },
                    }
                )
            return {
                "mode": self.RESIDENCY_MODE,
                "entries": len(self._tiered),
                "residentRows": sum(
                    e.resident_rows() for e in self._tiered.values()
                ),
                "rowsPromoted": self.rows_promoted,
                "rowsDemoted": self.rows_demoted,
                "coldUploads": self.cold_uploads,
                "evictions": dict(self.evictions),
                "bytesByContainer": dict(self._container_bytes),
                "budgetBytes": self.STACK_BYTES_BUDGET,
                "tiers": per_entry,
            }


# ------------------------------------------------------------------ plans
class _Planner:
    """Builds (closure, leaf inputs, structure key) for one call tree.

    ``block_shape`` is the [S, W] plane shape the closures trace against:
    the global (len(shards), WORDS_PER_SHARD) for single-program jit, or
    the per-device block when the closure will run inside a shard_map
    program (zero leaves must be block-shaped there — a global-shaped
    zeros would shape-mismatch every sharded operand)."""

    def __init__(
        self,
        idx: Index,
        shards: list[int],
        stacks: StackCache,
        block_shape: tuple[int, int] | None = None,
    ):
        self.idx = idx
        self.shards = shards
        self.stacks = stacks
        self.block_shape = block_shape or (len(shards), WORDS_PER_SHARD)
        self._builders: list[Callable[[], Any]] = []  # device-input thunks
        self.scalars: list = []  # traced row-id/slot inputs (int | thunk)
        self._array_keys: dict[tuple, int] = {}
        # over-budget fields: rows each query leaf needs, resolved to an
        # atomic (dev, slots) snapshot at materialize time
        self._hot_needs: dict[tuple, tuple[Field, str, list[int]]] = {}
        self._hot_resolved: dict[tuple, tuple] = {}
        # tiered-mode needs: (row, container kind) pairs per field,
        # resolved via ONE atomic tiered_batch snapshot each
        self._tiered_needs: dict[tuple, tuple[Field, str, list]] = {}
        self._tiered_resolved: dict[tuple, tuple] = {}
        # (leaf structure key, count closure) for sparse/run leaves —
        # Count(Row) over a compressed row skips the plane entirely
        self.direct_counts: list[tuple[str, Callable]] = []

    def _add_array(self, key: tuple, build: Callable[[], Any]) -> int:
        i = self._array_keys.get(key)
        if i is None:
            i = len(self._builders)
            self._array_keys[key] = i
            self._builders.append(build)
        return i

    def materialize(self) -> list[Any]:
        """Resolve device inputs AFTER planning finishes. Hot-row fields
        resolve here as ONE atomic hot_batch per field — plan-time slot
        binding could go stale if a concurrent query evicted a row
        between planning and dispatch; the batch snapshot cannot."""
        for fkey, (field, view_name, rows) in self._hot_needs.items():
            self._hot_resolved[fkey] = self.stacks.hot_batch(
                self.idx, field, view_name, self.shards, rows
            )
        for fkey, (field, view_name, needs) in self._tiered_needs.items():
            self._tiered_resolved[fkey] = self.stacks.tiered_batch(
                self.idx, field, view_name, self.shards, needs
            )
        return [b() for b in self._builders]

    def scalar_values(self) -> list[int]:
        """Concrete traced-scalar inputs; call AFTER materialize() (hot
        slots resolve there)."""
        return [s() if callable(s) else s for s in self.scalars]

    def _add_scalar(self, value: int) -> int:
        self.scalars.append(int(value))
        return len(self.scalars) - 1

    def _matrix_leaf(self, field: Field, view_name: str, row_id: int):
        """closure(arrays, scalars) → uint32[S, W] for one stored row.

        Small fields read a slot of the full dense stack; over-budget
        fields promote the row into the hot slot stack and read that
        slot instead (same closure shape — only the traced index
        differs)."""
        try:
            # probing the budget up front keeps one compiled program per
            # (field mode); the check allocates nothing
            self.stacks.matrix(self.idx, field, view_name, self.shards)
            ai = self._add_array(
                ("m", field.name, view_name),
                lambda: self.stacks.matrix(
                    self.idx, field, view_name, self.shards
                )[0],
            )
            si = self._add_scalar(row_id)
            mode = "m"
        except StackOverBudget:
            if self.stacks.residency_mode() != "slots":
                return self._tiered_leaf(field, view_name, row_id)
            fkey = (field.name, view_name)
            need = self._hot_needs.setdefault(fkey, (field, view_name, []))
            if row_id >= 0:
                need[2].append(row_id)
            ai = self._add_array(
                ("hot",) + fkey, lambda: self._hot_resolved[fkey][0]
            )
            self.scalars.append(
                lambda: self._hot_resolved[fkey][1].get(row_id, -1)
            )
            si = len(self.scalars) - 1
            mode = "hot"

        def run(arrays, scalars):
            m = arrays[ai]
            row = scalars[si]
            # out-of-range / -1 rows read as zeros; axis 0 of the
            # row-major stack — a contiguous [S, W] plane, so the slice
            # reads only this row's bytes (see stack_view_matrices).
            # dynamic_index_in_dim + select rather than jnp.take: a
            # scalar take lowers to a gather HLO, which XLA may
            # materialize as its own HBM-sized temp before the consumer
            # op; dynamic-slice fuses into the consumer (the AND/popcount
            # chain), keeping a query's traffic at the rows it touches.
            r = jnp.clip(row, 0, m.shape[0] - 1)
            plane = jax.lax.dynamic_index_in_dim(m, r, axis=0, keepdims=False)
            valid = (row >= 0) & (row < m.shape[0])
            return jnp.where(valid, plane, jnp.uint32(0))

        return run, f"row({mode}:{field.name}/{view_name})"

    def _tiered_leaf(self, field: Field, view_name: str, row_id: int):
        """Row leaf of an over-budget field in tiered residency mode
        (docs/device-residency.md): the closure decodes the row's
        COMPRESSED container inside the consuming program — the kind is
        static (it is part of the structure key, so each kind combination
        compiles once) and the traced scalar is the container-store slot.
        Cold (pre-promotion) rows serve via a one-shot host-packed plane
        input instead — host-served, merged exactly on device."""
        kind, action = self.stacks.tiered_plan(
            self.idx, field, view_name, self.shards, row_id
        )
        n_s, n_w = len(self.shards), WORDS_PER_SHARD
        if action == "cold":
            ai = self._add_array(
                ("cold", field.name, view_name, row_id),
                lambda: self.stacks.cold_plane(
                    self.idx, field, view_name, self.shards, row_id
                ),
            )
            # the array ORDINAL must be part of the structure key: cold
            # arrays are per-row inputs (unlike the shared dense/tiered
            # stores), so Union(Row(7), Row(7)) — one deduped input —
            # and Union(Row(8), Row(9)) — two — are different program
            # structures that a row-blind key would alias
            return (
                lambda arrays, scalars: arrays[ai]
            ), f"row(cold{ai}:{field.name}/{view_name})"
        fkey = (field.name, view_name)
        need = self._tiered_needs.setdefault(fkey, (field, view_name, []))
        need[2].append((row_id, kind))
        ai = self._add_array(
            ("tier", kind) + fkey,
            lambda: self._tiered_resolved[fkey][0][kind],
        )
        self.scalars.append(
            lambda: self._tiered_resolved[fkey][1].get(row_id, -1)
        )
        si = len(self.scalars) - 1
        skey = f"row(tier-{kind}:{field.name}/{view_name})"

        def gather(arrays, scalars):
            st = arrays[ai]
            slot = scalars[si]
            s = jnp.clip(slot, 0, st.shape[0] - 1)
            payload = jax.lax.dynamic_index_in_dim(
                st, s, axis=0, keepdims=False
            )
            return payload, slot >= 0

        if kind == "dense":

            def run(arrays, scalars):
                plane, valid = gather(arrays, scalars)
                return jnp.where(valid, plane, jnp.uint32(0))

        elif kind == "sparse":

            def run(arrays, scalars):
                ids, valid = gather(arrays, scalars)
                ids = jnp.where(valid, ids, jnp.int32(-1))
                return ops.containers.sparse_plane(ids, n_s, n_w)

            self.direct_counts.append(
                (
                    skey,
                    lambda arrays, scalars: ops.containers.sparse_count(
                        jnp.where(
                            gather(arrays, scalars)[1],
                            gather(arrays, scalars)[0],
                            jnp.int32(-1),
                        )
                    ),
                )
            )
        elif kind == "run":

            def run(arrays, scalars):
                runs, valid = gather(arrays, scalars)
                runs = jnp.where(valid, runs, jnp.int32(0))
                return ops.containers.run_plane(runs, n_s, n_w)

            self.direct_counts.append(
                (
                    skey,
                    lambda arrays, scalars: ops.containers.run_count(
                        jnp.where(
                            gather(arrays, scalars)[1],
                            gather(arrays, scalars)[0],
                            jnp.int32(0),
                        )
                    ),
                )
            )
        else:
            raise PlanError(f"unknown container kind {kind!r}")
        return run, skey

    def _existence(self):
        ef = self.idx.field(EXISTENCE_FIELD)
        if not self.idx.options.track_existence:
            raise PlanError(
                "query requires existence tracking (index created with "
                "track_existence=false)"
            )
        if ef is None:
            return (lambda arrays, scalars: jnp.zeros(
                self.block_shape, jnp.uint32
            )), "exists(empty)"
        return self._matrix_leaf(ef, VIEW_STANDARD, 0)

    def _bsi(self, field: Field):
        """closure → uint32[D, S, W] bit-slice block (row-major stack).

        Over-budget BSI stacks (huge shard lists) serve through the
        tiered residency layer in tiered mode: each slice row is its own
        container leaf — sign/existence slices tend to pack as runs,
        high-significance slices as sparse ids — and the closure stacks
        the decoded planes into the [D, S, W] block the BSI kernels
        expect (a transient inside the program, never a resident copy)."""
        need = BSI_OFFSET + field.bit_depth
        try:
            self.stacks.matrix(self.idx, field, VIEW_BSI, self.shards)
        except StackOverBudget:
            if self.stacks.residency_mode() == "slots":
                raise
            subs = [
                self._matrix_leaf(field, VIEW_BSI, d) for d in range(need)
            ]
            fns = [s[0] for s in subs]
            keys = ",".join(s[1] for s in subs)

            def run_tiered(arrays, scalars):
                return jnp.stack([fn(arrays, scalars) for fn in fns])

            return run_tiered, f"bsitier({field.name}:{keys})"
        ai = self._add_array(
            ("bsi", field.name),
            lambda: self.stacks.matrix(self.idx, field, VIEW_BSI, self.shards)[0],
        )

        def run(arrays, scalars):
            m = arrays[ai]
            if m.shape[0] < need:
                m = jnp.pad(m, ((0, need - m.shape[0]), (0, 0), (0, 0)))
            return m[:need]

        return run, f"bsi({field.name}:{field.bit_depth})"

    # ---------------------------------------------------------- call tree
    def plan(self, call: Call):
        """→ (closure(arrays, scalars) → uint32[S, W], structure key)"""
        name = call.name
        if name in ("Row", "Range"):
            return self._plan_row(call)
        if name in ("Union", "Intersect", "Difference", "Xor"):
            subs = [self.plan(ch) for ch in call.children]
            if not subs:
                if name == "Intersect":
                    raise PlanError("Intersect() needs at least one child")
                zero = lambda arrays, scalars: jnp.zeros(
                    self.block_shape, jnp.uint32
                )
                return zero, f"{name}()"
            fns = [s[0] for s in subs]
            keys = ",".join(s[1] for s in subs)
            op = {
                "Union": jnp.bitwise_or,
                "Intersect": jnp.bitwise_and,
                "Xor": jnp.bitwise_xor,
                "Difference": lambda a, b: a & ~b,
            }[name]

            def run(arrays, scalars):
                out = fns[0](arrays, scalars)
                for fn in fns[1:]:
                    out = op(out, fn(arrays, scalars))
                return out

            return run, f"{name}({keys})"
        if name == "Not":
            if len(call.children) != 1:
                raise PlanError("Not() takes exactly one call")
            sub, key = self.plan(call.children[0])
            ex, exkey = self._existence()
            return (
                lambda arrays, scalars: ex(arrays, scalars) & ~sub(arrays, scalars)
            ), f"Not({key},{exkey})"
        if name == "All":
            ex, exkey = self._existence()
            return ex, f"All({exkey})"
        if name == "Shift":
            if len(call.children) != 1:
                raise PlanError("Shift() takes exactly one call")
            n = call.arg("n", 1)
            if not isinstance(n, int) or n < 0:
                raise PlanError(f"Shift() n must be a non-negative integer, got {n!r}")
            sub, key = self.plan(call.children[0])
            return (
                lambda arrays, scalars: ops.shift_words(sub(arrays, scalars), n)
            ), f"Shift{n}({key})"
        raise PlanError(f"{name!r} is not a bitmap call")

    def _plan_row(self, call: Call):
        cond = call.condition()
        if cond is not None:
            return self._plan_condition(call, cond)
        fa = call.field_arg()
        if fa is None:
            raise PlanError(f"Row() needs a field argument: {call!r}")
        fname, row = fa
        field = self.idx.field(fname)
        if field is None:
            raise PlanError(f"field {fname!r} not found")
        row_id = self._row_id(field, row)

        ts_from, ts_to = call.arg("from"), call.arg("to")
        if ts_from is not None or ts_to is not None:
            if field.options.field_type != FIELD_TIME:
                raise PlanError(f"field {fname!r} is not a time field")
            raw_from, raw_to = ts_from, ts_to
            ts_from = coerce_timestamp(ts_from) if ts_from is not None else None
            ts_to = coerce_timestamp(ts_to) if ts_to is not None else None
            if raw_from is not None and ts_from is None:
                raise PlanError(f"bad from= timestamp {raw_from!r}")
            if raw_to is not None and ts_to is None:
                raise PlanError(f"bad to= timestamp {raw_to!r}")
            bounds = field.time_bounds()
            if bounds is None:
                zero = lambda arrays, scalars: jnp.zeros(
                    self.block_shape, jnp.uint32
                )
                return zero, "time(empty)"
            ts_from = ts_from if ts_from is not None else bounds[0]
            ts_to = ts_to if ts_to is not None else bounds[1]
            view_names = [
                v
                for v in views_by_time_range(
                    VIEW_STANDARD, ts_from, ts_to, field.options.time_quantum
                )
                if field.view(v) is not None
            ]
            subs = [self._matrix_leaf(field, v, row_id) for v in view_names]
            if not subs:
                zero = lambda arrays, scalars: jnp.zeros(
                    self.block_shape, jnp.uint32
                )
                return zero, "time(empty)"
            fns = [s[0] for s in subs]
            keys = ",".join(s[1] for s in subs)

            def run(arrays, scalars):
                out = fns[0](arrays, scalars)
                for fn in fns[1:]:
                    out = out | fn(arrays, scalars)
                return out

            return run, f"timeunion({keys})"
        return self._matrix_leaf(field, VIEW_STANDARD, row_id)

    def _plan_condition(self, call: Call, cond: tuple[str, Condition]):
        fname, condition = cond
        field = self.idx.field(fname)
        if field is None:
            raise PlanError(f"field {fname!r} not found")
        if field.options.field_type != FIELD_INT:
            raise PlanError(f"field {fname!r} is not an int field")
        bsi, bkey = self._bsi(field)
        ex, _ = self._existence() if condition.value is None and condition.op == "==" else (None, None)

        value = condition.value
        op = condition.op
        if value is None:
            if op == "!=":
                return (
                    lambda arrays, scalars: bsi(arrays, scalars)[0]
                ), f"notnull({bkey})"
            if op == "==":
                return (
                    lambda arrays, scalars: ex(arrays, scalars)
                    & ~bsi(arrays, scalars)[0]
                ), f"isnull({bkey})"
            raise PlanError(f"null only supports ==/!= comparisons, got {op!r}")

        # vmap over the shard axis (axis 1 of the [D, S, W] block)
        vmapped_between = jax.vmap(ops.bsi.between, in_axes=(1, None, None))
        vmapped_cmp = jax.vmap(ops.bsi.compare, in_axes=(1, None, None))
        if op == "between":
            lo, hi = int(value[0]), int(value[1])
            return (
                lambda arrays, scalars: vmapped_between(bsi(arrays, scalars), lo, hi)
            ), f"between[{lo},{hi}]({bkey})"
        v = int(value)
        return (
            lambda arrays, scalars: vmapped_cmp(bsi(arrays, scalars), op, v)
        ), f"cmp[{op}{v}]({bkey})"

    def _row_id(self, field: Field, row: Any) -> int:
        if isinstance(row, bool):
            return int(row)
        if isinstance(row, int):
            return row
        if isinstance(row, str):
            if not field.options.keys:
                raise PlanError(f"field {field.name!r} does not use string keys")
            rid = field.row_keys.translate_key(row, create=False)
            return rid if rid is not None else -1
        raise PlanError(f"bad row value {row!r}")


# ----------------------------------------------------------- compiled API
class QueryCompiler:
    """Caches jitted programs keyed by (index, structure, mode).

    The stacked arrays are built for the exact shard list of each query
    (the stack cache keys on it), so programs need no shard mask — two
    different shard subsets of the same length share one compiled program
    and differ only in their inputs.
    """

    def __init__(self, mesh_ctx=None, stats=None):
        self.stacks = StackCache(mesh_ctx, stats=stats)
        self.mesh_ctx = mesh_ctx
        self._programs: dict[tuple, Callable] = {}
        self._ones: dict[int, Any] = {}
        self._aot: set[tuple] = set()
        self._scalar_arrays: dict[tuple, Any] = {}
        # the HOST compilation layer: numpy plans over host-resident
        # stacks, memoized per plan key (executor/hostpath.py). Hangs off
        # the compiler so both engines — and their caches — share one
        # owner; the executor's router picks which one a call runs on.
        from pilosa_tpu.executor.hostpath import HostEngine

        self.host = HostEngine()
        # the MESH compilation layer: explicit shard_map programs with
        # psum reduction trees over the (shards × words) mesh — the
        # router's third path (docs/spmd.md). Only attached for a real
        # multi-device mesh; a 1-device mesh compiles to the identical
        # program with placement overhead on top.
        self.mesh_engine = None
        if mesh_ctx is not None and getattr(mesh_ctx, "n_devices", 1) > 1:
            from pilosa_tpu.parallel.mesh import MeshQueryEngine

            self.mesh_engine = MeshQueryEngine(mesh_ctx.mesh)

    def device_scalars(self, values: list[int]):
        """Device-resident int32 operand vector, cached by VALUE.

        Dispatching with a fresh numpy array uploads it host→device on
        every call; on a tunneled accelerator that upload is a transport
        round that can dominate the per-query cost of a fully pipelined
        dispatch (the compute for a 10B-column count is ~3 ms; the
        operand upload is pure overhead). Repeated queries — the common
        serving case, and exactly what a QPS benchmark issues — hit this
        cache and dispatch with zero transfers."""
        key = tuple(values)
        cached = self._scalar_arrays.get(key)
        if cached is None:
            if len(self._scalar_arrays) >= 4096:
                # tiny (≤ a few hundred bytes each); drop-all beats LRU
                # bookkeeping on the hot path, rebuild is one upload
                self._scalar_arrays.clear()
            host = np.asarray(key, dtype=np.int32)
            if self.mesh_ctx is not None:
                # replicate explicitly (and in ONE placement — not
                # asarray-then-re-place) so SPMD programs see a committed
                # sharding instead of inferring one per call
                cached = jax.device_put(
                    host,
                    jax.sharding.NamedSharding(
                        self.mesh_ctx.mesh, jax.sharding.PartitionSpec()
                    ),
                )
            else:
                cached = jnp.asarray(host)
            self._scalar_arrays[key] = cached
        return cached

    def program(self, key: tuple, build: Callable[[], Callable]) -> Callable:
        """Generic compiled-program cache (used by the executor for its
        aggregate programs as well)."""
        prog = self._programs.get(key)
        if prog is None:
            prog = build()
            self._programs[key] = prog
        return prog

    @staticmethod
    def _abstract(x):
        if not isinstance(x, (np.ndarray, jax.Array)):
            return x  # static scalars (incl. numpy scalars) pass through
        sh = getattr(x, "sharding", None)
        if sh is not None and not isinstance(sh, jax.sharding.NamedSharding):
            # single-device arrays lower WITHOUT a sharding annotation:
            # the unannotated AOT compile was measured fast through the
            # remote-compile tunnel and the concrete call reuses its
            # executable; mesh (NamedSharding) args keep theirs so the
            # SPMD program compiles against the real placement
            sh = None
        return jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=sh)

    def call_program(self, key: tuple, prog: Callable, *args):
        """Call a jitted program, explicitly AOT-compiling it first the
        first time each (key, arg-shapes) pair is seen.

        jit's lazy compile-on-__call__ path can be pathologically slow on
        a remote/tunneled accelerator (measured 2026-07-30: ~60 s at 2k
        shards, ~400 s at 10k, for a program that .lower().compile()
        builds in under a second — and unlike the lazy path, explicit AOT
        also hits the persistent compilation cache). Shardings of
        committed device args are carried into the abstract signature so
        the subsequent concrete call reuses the executable exactly."""
        if not hasattr(prog, "lower"):  # plain callable (e.g. test wrapper)
            return prog(*args)
        # one flat traversal of hashable leaf attributes — no struct or
        # string construction on the per-query hot path; ShapeDtypeStructs
        # are built only on an AOT-cache miss
        sig = key + tuple(
            (np.shape(x), x.dtype, getattr(x, "sharding", None))
            for x in jax.tree_util.tree_leaves(args)
            if isinstance(x, (np.ndarray, jax.Array))
        )
        if sig not in self._aot:
            shapes = jax.tree_util.tree_map(self._abstract, args)
            prog.lower(*shapes).compile()
            self._aot.add(sig)
        return prog(*args)

    def run_program(self, key: tuple, build: Callable[[], Callable], *args):
        """program() + call_program() in one step — the call-site sugar
        the executor uses for its aggregate programs."""
        return self.call_program(key, self.program(key, build), *args)

    def wrapped_program(self, key: tuple, build: Callable[[], Callable]):
        """program() + a call-later closure through call_program — for
        call sites that bind the program once and invoke it repeatedly."""
        prog = self.program(key, build)
        return lambda *a: self.call_program(key, prog, *a)

    def ones(self, n_shards: int):
        """Cached all-ones filter [S, W] on device."""
        cached = self._ones.get(n_shards)
        if cached is None:
            cached = jnp.full(
                (n_shards, WORDS_PER_SHARD), 0xFFFFFFFF, dtype=jnp.uint32
            )
            if self.mesh_ctx is not None:
                cached = self.mesh_ctx.place_rows(cached)
            self._ones[n_shards] = cached
        return cached

    def _plan(self, idx: Index, call: Call, shards: list[int]):
        planner = _Planner(idx, shards, self.stacks)
        run, skey = planner.plan(call)
        return planner, run, skey

    def bitmap_device(self, idx: Index, call: Call, shards: list[int]):
        """Evaluate a bitmap call for all shards in one program →
        device uint32[S, W]."""
        planner, run, skey = self._plan(idx, call, shards)
        key = (idx.name, len(shards), skey, "words")
        prog = self.program(
            key, lambda: jax.jit(lambda arrays, scalars: run(arrays, scalars))
        )
        arrays = planner.materialize()
        return self.call_program(
            key, prog, arrays, self.device_scalars(planner.scalar_values())
        )

    def bitmap_words(self, idx: Index, call: Call, shards: list[int]) -> np.ndarray:
        return np.asarray(self.bitmap_device(idx, call, shards))

    def count_async(self, idx: Index, call: Call, shards: list[int]):
        """Device int64 scalar (not synced) — lets callers pipeline many
        queries before paying the device→host readback latency.

        When the whole tree is ONE sparse/run container leaf (tiered
        residency), the count reads the compressed payload directly —
        O(payload) values, no [S, W] plane even transiently."""
        planner, run, skey = self._plan(idx, call, shards)
        direct = None
        if len(planner.direct_counts) == 1 and planner.direct_counts[0][0] == skey:
            direct = planner.direct_counts[0][1]
        key = (
            idx.name,
            len(shards),
            skey,
            "count-direct" if direct is not None else "count",
        )

        def build():
            if direct is not None:
                return jax.jit(direct)

            @jax.jit
            def prog(arrays, scalars):
                words = run(arrays, scalars)
                return jnp.sum(ops.popcount_rows(words).astype(jnp.int64))

            return prog

        prog = self.program(key, build)
        arrays = planner.materialize()
        return self.call_program(
            key, prog, arrays, self.device_scalars(planner.scalar_values())
        )

    def tiered_bsi_block(self, idx: Index, field: Field, shards: list[int]):
        """[D, S, W] bit-slice block of an over-budget int field,
        assembled on device from tiered compressed slice rows (the
        executor's aggregate paths feed it to their Sum/Min/Max/TopN
        programs; the block is a program OUTPUT, not a resident stack)."""
        planner = _Planner(idx, shards, self.stacks)
        run, skey = planner._bsi(field)
        key = (idx.name, len(shards), skey, "bsi_block")
        prog = self.program(key, lambda: jax.jit(run))
        arrays = planner.materialize()
        return self.call_program(
            key, prog, arrays, self.device_scalars(planner.scalar_values())
        )

    # ------------------------------------------------------ mesh programs
    # The explicit-SPMD (shard_map) compile path. Planner closures are the
    # SAME ones the single-program path uses — planned against the mesh's
    # per-device block shape so zero leaves trace block-shaped — and the
    # MeshQueryEngine wraps them in shard_map with the psum reduction
    # trees. Program/AOT caching rides the same caches as every other
    # program ("mesh" + spec mode in the key).

    def mesh_mode(self, n_shards: int) -> str | None:
        """The mesh placement mode serving this shard count, or None when
        no mesh is attached / the shapes only replicate (no mesh program)."""
        if self.mesh_engine is None:
            return None
        return self.mesh_engine.spec_mode(n_shards, WORDS_PER_SHARD)

    def mesh_plan(self, idx: Index, call: Call, shards: list[int], mode: str):
        """(planner, run, skey) with block-shaped zero leaves for ``mode``."""
        planner = _Planner(
            idx,
            shards,
            self.stacks,
            block_shape=self.mesh_engine.block_shape(
                len(shards), WORDS_PER_SHARD, mode
            ),
        )
        run, skey = planner.plan(call)
        return planner, run, skey

    def _mesh_dispatch(self, name: str, key: tuple, prog, *args):
        """Issue one mesh program: spanned per program (the
        ``mesh.dispatch`` trace surface) and counted for /debug/vars."""
        from pilosa_tpu.utils.tracing import GLOBAL_TRACER

        eng = self.mesh_engine
        eng.note_call(name)
        with GLOBAL_TRACER.span(
            "mesh.dispatch", program=name, devices=eng.n_devices
        ):
            return self.call_program(key, prog, *args)

    def mesh_bitmap_device(self, idx: Index, call: Call, shards: list[int]):
        """Bitmap call tree as ONE shard_map program → sharded
        uint32[S, W] (elementwise per device block; no collectives)."""
        mode = self.mesh_mode(len(shards))
        planner, run, skey = self.mesh_plan(idx, call, shards, mode)
        key = (idx.name, len(shards), skey, "mesh", mode, "words")
        prog = self.program(
            key, lambda: self.mesh_engine.bitmap_tree(run, mode)
        )
        arrays = planner.materialize()
        return self._mesh_dispatch(
            "bitmap",
            key,
            prog,
            arrays,
            self.device_scalars(planner.scalar_values()),
        )

    def mesh_bitmap_words(self, idx: Index, call: Call, shards: list[int]) -> np.ndarray:
        """Synchronous mesh bitmap: the gather of the sharded result IS a
        collective readback — spanned as ``mesh.collective`` so the query
        trace shows where the cross-chip transfer happened."""
        from pilosa_tpu.utils.tracing import GLOBAL_TRACER

        dev = self.mesh_bitmap_device(idx, call, shards)
        with GLOBAL_TRACER.span(
            "mesh.collective", program="bitmap",
            devices=self.mesh_engine.n_devices,
        ):
            return np.asarray(dev)

    def mesh_count_async(self, idx: Index, call: Call, shards: list[int]):
        """Count as one shard_map program → replicated int64 (not
        synced); rides the same readback wave as every other pending."""
        mode = self.mesh_mode(len(shards))
        planner, run, skey = self.mesh_plan(idx, call, shards, mode)
        key = (idx.name, len(shards), skey, "mesh", mode, "count")
        prog = self.program(
            key, lambda: self.mesh_engine.count_tree(run, mode)
        )
        arrays = planner.materialize()
        return self._mesh_dispatch(
            "count",
            key,
            prog,
            arrays,
            self.device_scalars(planner.scalar_values()),
        )

    def mesh_snapshot(self) -> dict:
        """/debug/vars ``meshExecution`` section."""
        if self.mesh_engine is None:
            return {"attached": False}
        out = {"attached": True}
        out.update(self.mesh_engine.snapshot())
        return out

