"""Client-side bulk loader — the wire-speed ingest lane's front half
(docs/ingest.md).

Reads CSV / JSONL (= NDJSON) bit records, partitions them by shard,
builds serialized roaring container payloads with the vectorized
builders in ``roaring/build.py`` (sort → shard-split → columnar
container passes — never a per-bit ``Set``), and streams the frames to
``POST /index/{i}/field/{f}/import-roaring/{shard}`` over a bounded
pipeline of keep-alive connections, honoring the server's 429 /
Retry-After compaction-debt admission gate (the retry IS the protocol:
the server sheds load when durability can't keep up, the loader paces
itself to it).

Used by ``pilosa_tpu import --roaring`` and by ``bench_all.py``'s
sustained-ingest row; the public entry points are ``parse_records`` and
``bulk_load``.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
import urllib.parse

import numpy as np

from pilosa_tpu.roaring import build as roaring_build
from pilosa_tpu.roaring.serialize import serialize
from pilosa_tpu.shardwidth import SHARD_WIDTH

# positions per frame: bounds client memory and per-POST latency while
# keeping the per-request overhead (HTTP round trip + WAL append +
# barrier) amortized over ~a shard's worth of bits
DEFAULT_BATCH_BITS = 1 << 20
DEFAULT_PIPELINE = 4
MAX_RETRIES_429 = 64  # a wedged compactor fails loudly, eventually


class LoaderError(RuntimeError):
    pass


def detect_format(path: str) -> str:
    """File-extension format sniff: .csv → csv, .jsonl/.ndjson/.json →
    jsonl; anything else defaults to csv (the reference importer's
    format)."""
    p = path.lower()
    if p.endswith((".jsonl", ".ndjson", ".json")):
        return "jsonl"
    return "csv"


def parse_records(lines, fmt: str = "csv") -> tuple[np.ndarray, np.ndarray]:
    """Parse bit records into (rows, cols) uint64 vectors.

    csv: ``rowID,columnID`` per line (extra columns ignored — the
    timestamp column of the reference's import format is not part of
    the roaring lane, which writes the standard view only).
    jsonl/ndjson: one object per line; keys ``rowID``/``row`` and
    ``columnID``/``col``/``column`` accepted."""
    rows: list[int] = []
    cols: list[int] = []
    if fmt in ("jsonl", "ndjson"):
        for line in lines:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            r = obj.get("rowID", obj.get("row"))
            c = obj.get("columnID", obj.get("col", obj.get("column")))
            if r is None or c is None:
                raise LoaderError(
                    f"jsonl record missing rowID/columnID: {line[:80]!r}"
                )
            rows.append(int(r))
            cols.append(int(c))
    elif fmt == "csv":
        for line in lines:
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if len(parts) < 2:
                raise LoaderError(f"csv record needs rowID,columnID: {line!r}")
            rows.append(int(parts[0]))
            cols.append(int(parts[1]))
    else:
        raise LoaderError(f"unknown format {fmt!r} (csv|jsonl|ndjson)")
    return (
        np.asarray(rows, dtype=np.uint64),
        np.asarray(cols, dtype=np.uint64),
    )


def build_frames(
    rows: np.ndarray,
    cols: np.ndarray,
    batch_bits: int = DEFAULT_BATCH_BITS,
    shard_width: int = SHARD_WIDTH,
) -> list[tuple[int, bytes, int]]:
    """(rows, cols) → ``[(shard, frame_bytes, n_bits), ...]`` via the
    no-sort columnar builder (roaring/build.py:shard_payloads). The
    input is pre-sliced to ``batch_bits`` records so one POST never
    carries more than that many positions (bounds client memory and
    per-request latency)."""
    rows = np.asarray(rows, dtype=np.uint64)
    cols = np.asarray(cols, dtype=np.uint64)
    out: list[tuple[int, bytes, int]] = []
    for i in range(0, max(cols.size, 1), batch_bits):
        out.extend(
            roaring_build.shard_payloads(
                rows[i : i + batch_bits],
                cols[i : i + batch_bits],
                shard_width,
            )
        )
    return out


class _Conn:
    """One keep-alive connection to the target host with transparent
    single-redial (the server reaps idle keep-alives; a long build gap
    between posts must not fail the batch)."""

    def __init__(self, base_uri: str, timeout: float, ssl_context=None):
        u = urllib.parse.urlsplit(base_uri)
        self.https = u.scheme == "https"
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if self.https else 80)
        self.timeout = timeout
        self.ssl_context = ssl_context
        self._conn = None

    def _connect(self):
        if self.https:
            return http.client.HTTPSConnection(
                self.host, self.port, timeout=self.timeout,
                context=self.ssl_context,
            )
        return http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )

    def post(self, path: str, body: bytes) -> tuple[int, bytes, str | None]:
        """POST with one transparent redial on a dead keep-alive socket.
        Returns (status, body, retry_after)."""
        for attempt in (0, 1):
            if self._conn is None:
                self._conn = self._connect()
            try:
                self._conn.request(
                    "POST", path, body,
                    headers={"Content-Type": "application/octet-stream"},
                )
                resp = self._conn.getresponse()
                data = resp.read()
                return resp.status, data, resp.headers.get("Retry-After")
            except (OSError, http.client.HTTPException):
                self.close()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None


def stream_load(
    base_uri: str,
    index: str,
    field: str,
    batches,
    *,
    view: str = "standard",
    pipeline: int = DEFAULT_PIPELINE,
    batch_bits: int = DEFAULT_BATCH_BITS,
    timeout: float = 60.0,
    ssl_context=None,
    shard_width: int = SHARD_WIDTH,
    stop=None,
) -> dict:
    """The sustained-ingest pipeline: ``batches`` yields (rows, cols)
    vector pairs; the calling thread BUILDS per-shard roaring frames
    (the vectorized columnar passes) while ``pipeline`` keep-alive
    workers STREAM already-built frames concurrently — construction and
    delivery overlap, so sustained throughput is bounded by the slower
    half, not their sum. The bounded queue applies backpressure to the
    builder when the server is the constraint.

    Returns a stats dict: bits/bytes/posts delivered, elapsed seconds
    (covering build AND delivery), sustained Mbit/s (million set bits
    per second), and 429-backoff counts. Every frame is either
    delivered (2xx after the server's durability barrier) or the load
    raises — no silent partial success; 429s back off per the server's
    Retry-After and retry the SAME frame (idempotent: the adopt is a
    union). ``stop`` (an optional ``threading.Event``) ends the load
    cleanly between batches — the bench's timed-phase cutoff."""
    work: queue.Queue = queue.Queue(maxsize=max(4, 4 * pipeline))
    n_workers = max(1, pipeline)
    errors: list[BaseException] = []
    stats_lock = threading.Lock()
    stats = {"bits": 0, "bytes": 0, "posts": 0, "backoffs429": 0, "frames": 0}
    path_base = f"/index/{index}/field/{field}/import-roaring"
    _DONE = object()

    def worker() -> None:
        conn = _Conn(base_uri, timeout, ssl_context)
        try:
            while True:
                item = work.get()
                if item is _DONE:
                    return
                if errors:
                    continue  # drain so the producer never blocks
                shard, frame, n_bits = item
                path = f"{path_base}/{shard}?view={view}"
                for _retry in range(MAX_RETRIES_429):
                    status, body, retry_after = conn.post(path, frame)
                    if status == 429:
                        # compaction-debt admission gate: the server is
                        # protecting crash-replay time — wait as told
                        with stats_lock:
                            stats["backoffs429"] += 1
                        try:
                            delay = float(retry_after or 0.1)
                        except ValueError:
                            delay = 0.1
                        time.sleep(min(max(delay, 0.01), 5.0))
                        continue
                    if status != 200:
                        raise LoaderError(
                            f"import-roaring shard {shard}: HTTP {status} "
                            f"{body[:200]!r}"
                        )
                    break
                else:
                    raise LoaderError(
                        f"import-roaring shard {shard}: still 429 after "
                        f"{MAX_RETRIES_429} backoffs (compactor wedged?)"
                    )
                with stats_lock:
                    stats["bits"] += n_bits
                    stats["bytes"] += len(frame)
                    stats["posts"] += 1
        except BaseException as e:  # noqa: BLE001 — re-raised by the caller
            errors.append(e)
            # keep draining until the sentinel: with every worker dead a
            # bounded-queue put in the producer would deadlock otherwise
            while work.get() is not _DONE:
                pass
        finally:
            conn.close()

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, daemon=True, name=f"bulk-load_{i}")
        for i in range(n_workers)
    ]
    for t in threads:
        t.start()
    try:
        for rows, cols in batches:
            if errors or (stop is not None and stop.is_set()):
                break
            for shard, frame, n_bits in build_frames(
                rows, cols, batch_bits, shard_width
            ):
                stats["frames"] += 1
                work.put((shard, frame, n_bits))
    finally:
        for _ in threads:
            work.put(_DONE)
        for t in threads:
            t.join()
    elapsed = time.perf_counter() - t0
    if errors:
        raise errors[0]
    stats["seconds"] = round(elapsed, 4)
    stats["mbitSetPerS"] = round(stats["bits"] / max(elapsed, 1e-9) / 1e6, 4)
    stats["pipeline"] = n_workers
    return stats


def bulk_load(
    base_uri: str,
    index: str,
    field: str,
    rows: np.ndarray,
    cols: np.ndarray,
    **kwargs,
) -> dict:
    """One-shot form of ``stream_load`` over a single (rows, cols)
    batch — the CLI's lane."""
    return stream_load(base_uri, index, field, [(rows, cols)], **kwargs)
