"""Durable file I/O: the ONE sanctioned write protocol for holder data.

The reference's durability story is "snapshot + append-only ops log with
atomic replace" (fragment.go snapshot/opN, PAPER.md). This module is
where that story actually becomes crash-safe (docs/durability.md):

- ``atomic_write_file`` — tmp write → fsync(file) → ``os.replace`` →
  fsync(parent dir). The dir fsync is not optional decoration: on a
  crash after rename but before the directory entry reaches disk, the
  rename itself can be lost and the file reverts to its old content (or
  to nothing, for a first write). Every snapshot/meta/schema write under
  the holder path goes through here — the ``durability`` analyzer rule
  bans bare write-mode ``open()`` under ``core/`` and ``os.replace``
  anywhere outside this module.
- WAL (ops-log) appends with a configurable acknowledgement fsync
  policy (config ``wal-fsync-mode``):

  * ``always`` — fsync inside every append (strongest, slowest);
  * ``batch``  — appends mark their file dirty; the durability barrier
    at the request acknowledgement point (``ack_barrier``, called by
    the API façade after every write request) group-fsyncs all dirty
    WAL files ONCE, coalescing with every other in-flight acknowledger
    of the same file (classic group commit);
  * ``off``    — no fsync (the pre-PR-8 behavior: page-cache-only,
    acknowledged writes can die with the OS).

- FS fault hooks: every primitive consults an installed hook
  (``parallel/faultinject.py``'s ``FSFaultInjector``) before touching
  the filesystem, so EIO/ENOSPC/partial-write/crash-at-named-point
  chaos is deterministic and reaches the write protocol exactly where
  real faults would. Hook ops: ``wal-append``, ``snapshot-write``
  (via the ``op`` argument), ``fsync``, ``rename``, ``dirfsync``,
  ``truncate``.
"""

from __future__ import annotations

import os
import threading

WAL_ALWAYS = "always"
WAL_BATCH = "batch"
WAL_OFF = "off"
WAL_MODES = (WAL_ALWAYS, WAL_BATCH, WAL_OFF)


class SimulatedCrash(BaseException):
    """A process death simulated at an exact point in the write
    protocol. BaseException on purpose: recovery code paths catch
    ``Exception``, and a simulated crash must tear through them exactly
    like SIGKILL would — only the test harness (and the compaction
    worker's crash containment) catches this."""


# ---------------------------------------------------------------- FS hook
_fs_hook = None


def install_fs_hook(hook) -> None:
    """Install (or clear, with None) the process-wide filesystem fault
    hook. Protocol: ``check(op, path)`` may raise OSError/SimulatedCrash
    or kill the process; ``write_cap(op, path, nbytes) -> int | None``
    returns how many bytes to actually write for a torn-write fault;
    after a capped write the layer calls ``torn(op, path)``, which must
    raise or kill."""
    global _fs_hook
    _fs_hook = hook


def fs_hook():
    return _fs_hook


def _check(op: str, path: str) -> None:
    h = _fs_hook
    if h is not None:
        h.check(op, path)


def _write(f, data: bytes, op: str, path: str) -> None:
    h = _fs_hook
    if h is not None:
        cap = h.write_cap(op, path, len(data))
        if cap is not None and cap < len(data):
            f.write(data[:cap])
            f.flush()
            h.torn(op, path)
            # torn() must not return; a hook bug would otherwise turn a
            # torn-write fault into a silent short write
            raise SimulatedCrash(f"torn {op} on {path}")
    f.write(data)


# ------------------------------------------------------------- primitives
def fsync_dir(dirpath: str) -> None:
    """fsync a DIRECTORY — makes a rename/create/unlink in it durable."""
    _check("dirfsync", dirpath)
    fd = os.open(dirpath or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_path(path: str) -> None:
    _check("fsync", path)
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_file(
    path: str,
    data: bytes | str,
    *,
    tmp_suffix: str = ".tmp",
    op: str = "write",
    durable: bool = True,
) -> None:
    """Crash-safe whole-file write: tmp → fsync → rename → dir fsync.

    A crash at ANY point leaves either the complete old content or the
    complete new content at ``path`` — never a torn mix. ``durable=
    False`` keeps the atomic-replace half but skips both fsyncs, for
    best-effort caches (probe verdicts, diagnostics) whose loss costs
    nothing."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    tmp = path + tmp_suffix
    _check(op, tmp)
    with open(tmp, "wb") as f:
        _write(f, data, op, tmp)
        f.flush()
        if durable:
            _check("fsync", tmp)
            os.fsync(f.fileno())
    replace_durable(tmp, path, durable=durable)


def write_new_file(
    path: str, data: bytes, *, op: str = "write", durable: bool = True
) -> None:
    """Write + fsync a file WITHOUT the rename step — the first half of
    a staged atomic write whose commit (``replace_durable``) the caller
    performs later (the compaction worker: snapshot body first, op-log
    tail carried over under the fragment lock, then the rename)."""
    _check(op, path)
    with open(path, "wb") as f:
        _write(f, data, op, path)
        f.flush()
        if durable:
            _check("fsync", path)
            os.fsync(f.fileno())


def append_file(
    path: str, data: bytes, *, op: str = "write", durable: bool = True
) -> None:
    """Append + fsync — for pre-rename staging files only (the fsync is
    unconditional of the WAL mode: these bytes are about to be COMMITTED
    by a rename, so they must be on disk first)."""
    _check(op, path)
    with open(path, "ab") as f:
        _write(f, data, op, path)
        f.flush()
        if durable:
            _check("fsync", path)
            os.fsync(f.fileno())


def replace_durable(src: str, dst: str, *, durable: bool = True) -> None:
    """``os.replace`` + parent-directory fsync — the sanctioned rename.
    Callers that produced ``src`` through an external tool (the native-
    kernel build) use this directly; everything else goes through
    ``atomic_write_file``."""
    _check("rename", dst)
    os.replace(src, dst)
    if durable:
        fsync_dir(os.path.dirname(os.path.abspath(dst)))


def truncate_file(path: str, size: int = 0, *, durable: bool = True) -> None:
    """Truncate in place (torn-tail repair, journal reset) + fsync."""
    _check("truncate", path)
    os.truncate(path, size)
    if durable:
        _fsync_path(path)


# ------------------------------------------------------------ WAL policy
_wal_mode = WAL_BATCH


def set_wal_fsync_mode(mode: str) -> None:
    if mode not in WAL_MODES:
        raise ValueError(
            f"wal-fsync-mode must be one of {WAL_MODES}, got {mode!r}"
        )
    global _wal_mode
    _wal_mode = mode


def wal_fsync_mode() -> str:
    return _wal_mode


class GroupFsync:
    """Group commit for WAL fsyncs: concurrent acknowledgers of the same
    file share one fsync syscall.

    ``mark(path)`` stamps a monotone sequence per dirty file;
    ``flush()`` fsyncs every file whose latest mark is newer than its
    last completed fsync. While one flusher is fsyncing a file, other
    flushers needing the same file WAIT for that fsync instead of
    issuing their own — and a mark taken before the fsync started is
    covered by it (fsync flushes everything written so far, through any
    descriptor of the inode)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._seq = 0
        self._pending: dict[str, int] = {}
        self._synced: dict[str, int] = {}
        self._syncing: set[str] = set()

    def mark(self, path: str) -> None:
        with self._cond:
            self._seq += 1
            self._pending[path] = self._seq

    def flush(self) -> None:
        with self._cond:
            goals = {}
            for p in list(self._pending):
                s = self._pending[p]
                if s > self._synced.get(p, 0):
                    goals[p] = s
                elif p not in self._syncing:
                    # clean and idle: retire the bookkeeping — without
                    # this, every WAL file ever marked (including dropped
                    # fragments') stays in the maps forever and every
                    # acknowledgement scans all of them. Re-marking
                    # recreates the entry.
                    del self._pending[p]
                    self._synced.pop(p, None)
        for path, goal in goals.items():
            self._flush_one(path, goal)

    def _flush_one(self, path: str, goal: int) -> None:
        with self._cond:
            while True:
                if self._synced.get(path, 0) >= goal:
                    return  # another flusher covered our writes
                if path not in self._syncing:
                    self._syncing.add(path)
                    break
                self._cond.wait(timeout=5.0)
            # everything marked up to HERE is on disk once our fsync
            # completes — claim it so waiters behind us are released too
            claim = self._pending.get(path, goal)
        ok = False
        try:
            _fsync_path(path)
            ok = True
        except FileNotFoundError:
            # the WAL file was deleted (fragment dropped in a resize
            # handoff) — nothing left to make durable
            ok = True
        finally:
            with self._cond:
                self._syncing.discard(path)
                if ok:
                    self._synced[path] = max(
                        self._synced.get(path, 0), claim
                    )
                self._cond.notify_all()

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "dirtyFiles": sum(
                    1
                    for p, s in self._pending.items()
                    if s > self._synced.get(p, 0)
                ),
            }


_group = GroupFsync()


def append_wal(path: str, data: bytes) -> None:
    """The sanctioned ops-log append: open-per-write (see
    Fragment._append_op for why no handle is retained), flushed to the
    OS, then made durable per the WAL fsync mode."""
    _check("wal-append", path)
    with open(path, "ab") as f:
        _write(f, data, "wal-append", path)
        f.flush()
        if _wal_mode == WAL_ALWAYS:
            _check("fsync", path)
            os.fsync(f.fileno())
    if _wal_mode == WAL_BATCH:
        _group.mark(path)


def open_wal(path: str, mode: str = "a"):
    """Open a retained append handle for a line-oriented WAL (the
    translate-key log keeps one — allocation rate makes open-per-write
    measurable there). Writers must call ``wal_written`` after flushing."""
    _check("wal-append", path)
    return open(path, mode)


def wal_write(f, data: str | bytes, path: str) -> None:
    """One append through a RETAINED WAL handle with the full durability
    contract applied: fault-hook check + (torn-write-capable) write,
    flush, then per-mode durability bookkeeping. The batched translate-
    key allocator writes one record batch per call — one append, one
    flush, one group-commit mark, regardless of how many keys the batch
    carries (docs/ingest.md)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    _check("wal-append", path)
    # text-mode handles (the translate log) can't take bytes: write via
    # the underlying buffer so the torn-write cap operates on raw bytes
    sink = f.buffer if hasattr(f, "buffer") else f
    _write(sink, data, "wal-append", path)
    f.flush()
    wal_written(path, f.fileno())


def wal_written(path: str, fileno: int | None = None) -> None:
    """Durability bookkeeping for a WAL write that already reached the
    OS (flushed): fsync now (``always``), mark for the next
    ``ack_barrier`` (``batch``), or nothing (``off``)."""
    if _wal_mode == WAL_ALWAYS:
        _check("fsync", path)
        if fileno is not None:
            os.fsync(fileno)
        else:
            _fsync_path(path)
    elif _wal_mode == WAL_BATCH:
        _group.mark(path)


def ack_barrier() -> None:
    """The durability barrier at a write request's acknowledgement
    point: in ``batch`` mode, group-fsync every WAL file dirtied since
    the last barrier. In ``always`` mode appends are already durable;
    in ``off`` mode durability is explicitly waived. The API façade
    calls this after every accepted write request, BEFORE the response
    leaves the server."""
    if _wal_mode == WAL_BATCH:
        _group.flush()


def wal_snapshot() -> dict:
    """Debug/metrics view of the WAL policy state."""
    return {"mode": _wal_mode, **_group.snapshot()}
