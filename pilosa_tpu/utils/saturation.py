"""Saturation probes: which in-process resource binds, measured.

BENCH_SWEEP_r07's loudest signal — ``sync_count_qps_c64`` collapsing to
0.96x c1 after scaling to 1.76x at c32 — was *asserted* to be "one event
loop + one GIL-bound worker pool" with no measured evidence for which
resource actually binds.  This module is that evidence, USE-style
(utilization / saturation / errors), feeding ``GET /debug/saturation``:

- **event-loop lag** — a periodic callback scheduled on the asyncio loop
  (server/eventloop.py's lag-probe task) records how late each wakeup
  fires.  A loop busy parsing heads or shipping responses wakes late;
  the lag histogram IS the loop's run-queue delay.
- **worker-pool utilization** — the same probe task samples each
  admission class's in-flight/limit fraction, so "the query lane spent
  the window at 100%" is a measured p95, not a guess from one scrape.
- **GIL-contention estimator** — a dedicated probe thread performs a
  no-op timed wait and measures how late the wakeup lands.  The OS
  marks the thread runnable on time; everything past the timer is time
  spent waiting to be *scheduled onto the interpreter* — dominated by
  the GIL under CPU-bound Python load (plus a bounded OS-scheduler
  term).  It is an estimator, not a GIL timer: calibrate against the
  idle baseline the bench row records.
- **lock contention** — ``ContendedLock`` wraps the hot serving locks
  (fragment, stack-cache, scheduler, holder) with a fast-path
  nonblocking attempt; only a *contended* acquire pays timing and
  emits ``lock_wait_seconds{lock}`` / ``lock_contended_total{lock}``.

``SaturationMonitor.report`` normalizes each probe into a pressure in
[0, 1] and names the binding resource for the window — the number the
multi-process PR (ROADMAP item 3) is sized from.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable

# module-level metrics sink, installed by Server.open (the hot locks are
# constructed deep inside core/executor where no StatsClient is in
# scope; a process serves one metrics registry, like GLOBAL_TRACER)
_stats = None


def set_stats(client) -> None:
    global _stats
    _stats = client


# pressure normalization constants (docs/profiling.md): the lag value at
# which a probe reports pressure 1.0.  Loop wakeups and GIL handoffs are
# sub-millisecond healthy; ~100ms loop lag / ~50ms GIL wait at p99 mean
# the resource is the bottleneck, not a blip (the GIL constant is 10
# switch intervals at the default 5ms sys.setswitchinterval).
LOOP_LAG_SATURATED_S = 0.100
GIL_WAIT_SATURATED_S = 0.050
# a lock family accumulating >= this many seconds of waiting per
# wall-clock second means roughly one full thread is parked on it
LOCK_WAIT_SATURATED_PER_S = 1.0
# pressures below this never name a binding resource — an idle process
# must report "none", not whichever probe's noise floor is highest
BINDING_FLOOR = 0.5


class LagRing:
    """Bounded ring of (monotonic, value) observations with windowed
    percentiles — the storage behind every saturation probe.  Appends
    are GIL-atomic deque ops; the windowed read copies then filters, so
    probes never block on a reporting scrape."""

    __slots__ = ("_events", "maxlen")

    def __init__(self, maxlen: int = 4096):
        self.maxlen = maxlen
        self._events: deque[tuple[float, float]] = deque(maxlen=maxlen)

    def observe(self, value: float, t: float | None = None) -> None:
        self._events.append(
            (t if t is not None else time.monotonic(), value)
        )

    def window(self, seconds: float) -> dict:
        """{count, p50, p95, p99, max, mean} over the last ``seconds``."""
        cutoff = time.monotonic() - seconds
        values = sorted(v for t, v in list(self._events) if t >= cutoff)
        n = len(values)
        if n == 0:
            return {"count": 0, "p50": 0.0, "p95": 0.0, "p99": 0.0,
                    "max": 0.0, "mean": 0.0}
        return {
            "count": n,
            "p50": values[n // 2],
            "p95": values[min(n - 1, int(n * 0.95))],
            "p99": values[min(n - 1, int(n * 0.99))],
            "max": values[-1],
            "mean": sum(values) / n,
        }


class LockFamily:
    """Aggregate contention counters for one NAMED lock family (every
    fragment's lock folds into the one "fragment" row — per-instance
    rows would be unreadable and unbounded).  Counter updates are plain
    ``+=`` on the GIL: a racing pair can lose one increment, never
    corrupt the value — the monitoring tradeoff Ewma documents."""

    __slots__ = ("name", "acquisitions", "contended", "wait_total_s", "events")

    def __init__(self, name: str):
        self.name = name
        self.acquisitions = 0
        self.contended = 0
        self.wait_total_s = 0.0
        self.events = LagRing(maxlen=2048)

    def record_contended(self, wait_s: float) -> None:
        self.contended += 1
        self.wait_total_s += wait_s
        self.events.observe(wait_s)
        if _stats is not None:
            _stats.count("lock_contended_total", tags={"lock": self.name})
            _stats.timing("lock_wait_seconds", wait_s, tags={"lock": self.name})

    def snapshot(self, window_s: float) -> dict:
        cutoff = time.monotonic() - window_s
        recent = [(t, v) for t, v in list(self.events._events) if t >= cutoff]
        return {
            "acquisitions": self.acquisitions,
            "contendedTotal": self.contended,
            "waitSecondsTotal": round(self.wait_total_s, 6),
            "windowContended": len(recent),
            "windowWaitSeconds": round(sum(v for _, v in recent), 6),
        }


_FAMILIES: dict[str, LockFamily] = {}
_families_lock = threading.Lock()


def lock_family(name: str) -> LockFamily:
    with _families_lock:
        fam = _FAMILIES.get(name)
        if fam is None:
            fam = _FAMILIES[name] = LockFamily(name)
        return fam


def lock_families_snapshot(window_s: float = 60.0) -> dict:
    with _families_lock:
        fams = list(_FAMILIES.values())
    return {f.name: f.snapshot(window_s) for f in fams}


class ContendedLock:
    """Drop-in Lock/RLock with per-family contention accounting.

    The uncontended path costs ONE extra nonblocking attempt (no clock
    read, no metric); only an acquire that actually blocks pays two
    monotonic reads and the family record.  Implements the full context
    protocol plus ``acquire``/``release``, so ``threading.Condition``
    wraps it unmodified (Condition's default ``_is_owned`` probes via
    ``acquire(False)``, which the fast path serves)."""

    __slots__ = ("_lock", "family")

    def __init__(self, name: str, reentrant: bool = False):
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self.family = lock_family(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(False):  # pilosa: allow(raw-acquire) — the
            # shim IS the guard: callers hold via with/try-finally
            self.family.acquisitions += 1
            return True
        if not blocking:
            return False
        t0 = time.monotonic()
        ok = self._lock.acquire(True, timeout)  # pilosa: allow(raw-acquire)
        if ok:
            # a timed-out acquire is NOT an acquisition and must not
            # charge its full timeout into the contention window — it
            # would inflate the saturation verdict with waits that
            # never turned into holds
            self.family.acquisitions += 1
            self.family.record_contended(time.monotonic() - t0)
        return ok

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "ContendedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self._lock.release()
        return False


class GILProbe:
    """The GIL-contention estimator: a daemon thread performing a no-op
    timed wait per tick and recording how far past the timer the wakeup
    actually lands.  The wait itself releases the GIL; re-entering the
    interpreter after the timeout requires re-acquiring it, so the
    overshoot is cross-thread scheduling delay — GIL wait plus a small
    OS-scheduler term."""

    def __init__(self, interval_s: float = 0.05, stats=None,
                 clock: Callable[[], float] = time.monotonic):
        self.interval_s = interval_s
        self.stats = stats
        self.lag = LagRing()
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="gil-probe"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # restartable: a later start() must spawn a fresh probe instead
        # of silently serving a frozen lag window
        self._thread = None
        self._stop = threading.Event()

    def _run(self) -> None:
        stop = self._stop  # the Event THIS run was started with
        while True:
            t0 = self._clock()
            if stop.wait(self.interval_s):
                return
            lag = max(0.0, self._clock() - t0 - self.interval_s)
            self.lag.observe(lag)
            if self.stats is not None:
                self.stats.timing("gil_wait_seconds", lag)


class SaturationMonitor:
    """One per serving front end: owns the GIL probe, receives the event
    loop's lag/utilization samples, and assembles the USE verdict.  The
    listener records into it from the loop; ``report`` is called from a
    handler thread — all storage is LagRing (lock-free enough)."""

    def __init__(self, stats=None, enabled: bool = True,
                 gil_interval_s: float = 0.05):
        self.stats = stats
        self.enabled = enabled
        self.loop_lag = LagRing()
        self.worker_util: dict[str, LagRing] = {}
        self.gil = GILProbe(interval_s=gil_interval_s, stats=stats)
        self._started = False

    def start(self) -> None:
        """Start the probe thread (Server.open; embedded listeners that
        never call this still serve loop-lag and lock rows)."""
        if self.enabled and not self._started:
            self._started = True
            self.gil.start()

    def stop(self) -> None:
        if self._started:
            self.gil.stop()
            self._started = False

    # ------------------------------------------------------------ intake
    def observe_loop_lag(self, lag_s: float) -> None:
        self.loop_lag.observe(lag_s)
        if self.stats is not None:
            self.stats.timing("eventloop_lag_seconds", lag_s)

    def observe_worker_util(self, cls: str, frac: float) -> None:
        ring = self.worker_util.get(cls)
        if ring is None:
            ring = self.worker_util[cls] = LagRing()
        ring.observe(frac)
        if self.stats is not None:
            self.stats.gauge("worker_utilization", frac, tags={"class": cls})

    # ------------------------------------------------------------ report
    def report(self, window_s: float = 60.0, serving: dict | None = None) -> dict:
        loop = self.loop_lag.window(window_s)
        gil = self.gil.lag.window(window_s)
        workers = {
            # snapshot first: the event-loop probe inserts the first
            # per-class rings concurrently with a scrape, and sorting a
            # growing dict raises RuntimeError
            cls: ring.window(window_s)
            for cls, ring in sorted(dict(self.worker_util).items())
        }
        locks = lock_families_snapshot(window_s)

        pressures: dict[str, float] = {}
        # worker-pool pressure: the QUERY lane's p95 sampled utilization
        # (the lane serving the sweep; write/control lanes report but a
        # saturated control lane is a different disease)
        q = workers.get("query")
        if q is not None and q["count"] > 0:
            pressures["worker-pool"] = min(1.0, q["p95"])
        if loop["count"] > 0:
            pressures["event-loop"] = min(
                1.0, loop["p99"] / LOOP_LAG_SATURATED_S
            )
        if gil["count"] > 0:
            pressures["gil"] = min(1.0, gil["p99"] / GIL_WAIT_SATURATED_S)
        for name, row in locks.items():
            if row["windowContended"]:
                pressures[f"lock:{name}"] = min(
                    1.0,
                    row["windowWaitSeconds"]
                    / max(window_s, 1e-9)
                    / LOCK_WAIT_SATURATED_PER_S,
                )

        binding = "none"
        if pressures:
            top = max(pressures, key=lambda k: pressures[k])
            if pressures[top] >= BINDING_FLOOR:
                binding = top
        verdict = (
            "no probe reports saturation over the window"
            if binding == "none"
            else f"{binding} is the binding resource "
                 f"(pressure {pressures[binding]:.2f})"
        )
        # scale-out recommendation (docs/multiprocess.md): worker-pool
        # and GIL pressure are PER-INTERPRETER ceilings — more threads
        # cannot help, more processes can.  Name the remedy and size it
        # from the host's cores; on a core-starved box the suggestion
        # is recorded but waived, since N processes would time-share
        # the same core (the bench's MULTICHIP_r06 waiver precedent).
        recommendation = None
        if binding in ("worker-pool", "gil"):
            cores = os.cpu_count() or 1
            recommendation = {
                "remedy": "serving-processes",
                "why": (
                    f"{binding} saturation is per-process: N shard-"
                    "owning server processes multiply both lanes "
                    "(docs/multiprocess.md)"
                ),
                "hostCores": cores,
                "suggestedProcesses": max(2, min(cores, 8)),
            }
            if cores < 2:
                recommendation["gate"] = (
                    f"waived: {cores} core — serving processes would "
                    "time-share it; the remedy applies on a multi-core "
                    "host"
                )
        ms = lambda s: round(s * 1e3, 3)
        out = {
            "enabled": self.enabled,
            "probesStarted": self._started,
            "windowSeconds": window_s,
            "eventLoop": {
                "samples": loop["count"],
                "lagP50Ms": ms(loop["p50"]),
                "lagP99Ms": ms(loop["p99"]),
                "lagMaxMs": ms(loop["max"]),
            },
            "gil": {
                "samples": gil["count"],
                "probeIntervalMs": ms(self.gil.interval_s),
                "waitP50Ms": ms(gil["p50"]),
                "waitP99Ms": ms(gil["p99"]),
                "waitMaxMs": ms(gil["max"]),
            },
            "workers": {
                cls: {
                    "samples": w["count"],
                    "utilizationP50": round(w["p50"], 4),
                    "utilizationP95": round(w["p95"], 4),
                    "utilizationMax": round(w["max"], 4),
                }
                for cls, w in workers.items()
            },
            "locks": locks,
            "serving": serving or {},
            "pressures": {k: round(v, 4) for k, v in sorted(pressures.items())},
            "binding": binding,
            "verdict": verdict,
        }
        if recommendation is not None:
            out["recommendation"] = recommendation
        return out


# ------------------------------------------------------------- process RSS
def rss_bytes() -> int | None:
    """Resident set size of this process, or None when unreadable."""
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS:"):
                    return int(ln.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        # ru_maxrss is KiB on Linux, bytes on macOS — and a PEAK, not
        # current; the /proc path above is authoritative where it exists
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak if sys.platform == "darwin" else peak * 1024
    except (ImportError, OSError, ValueError):
        return None


def memory_limit_bytes() -> int | None:
    """The cgroup memory ceiling this process runs under, if any."""
    for path in (
        "/sys/fs/cgroup/memory.max",  # cgroup v2
        "/sys/fs/cgroup/memory/memory.limit_in_bytes",  # cgroup v1
    ):
        try:
            with open(path) as f:
                raw = f.read().strip()
            if raw and raw != "max":
                limit = int(raw)
                # v1 reports "unlimited" as a huge page-rounded number
                if limit < (1 << 60):
                    return limit
        except (OSError, ValueError):
            continue
    return None
