"""Persisted device-probe verdicts (VERDICT #3b).

A wedged accelerator transport presents as an indefinite HANG inside
backend init, so every probe against it costs the full watchdog timeout
(300 s by default).  The verdict is a property of the HOST's transport,
not of one process — so it is persisted host-side with a short TTL:
within the TTL, the next boot (server or bench) decides in <1 s by
reading the file instead of re-paying the probe.

Callers honor only NEGATIVE verdicts across boots (a healthy probe is
cheap to re-run; a stale positive would skip the watchdog on a
transport that wedged in between) — positive verdicts are stored for
observability and freshness bookkeeping.

Location: ``$PILOSA_TPU_PROBE_CACHE`` if set (tests point it at a tmp
dir), else ``$XDG_CACHE_HOME/pilosa_tpu/device_probe.json``, else
``~/.cache/pilosa_tpu/device_probe.json``.  Verdicts key on the JAX
platform pin that was probed — a CPU-pinned probe result must not
answer for the accelerator.  All I/O is best-effort: an unwritable
cache degrades to probing every boot, never to an error.
"""

from __future__ import annotations

import json
import os
import time


def cache_path() -> str:
    env = os.environ.get("PILOSA_TPU_PROBE_CACHE")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    return os.path.join(base, "pilosa_tpu", "device_probe.json")


def load(ttl_s: float, pin: str = "") -> dict | None:
    """The cached verdict dict ({"ok": bool, "platform": str, ...}) if
    one exists for this platform pin and is younger than ``ttl_s``;
    None otherwise (including ttl_s <= 0 — TTL 0 disables the cache)."""
    if ttl_s <= 0:
        return None
    try:
        with open(cache_path(), "r", encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            return None
        if data.get("pin", "") != (pin or ""):
            return None
        # wall clock on purpose: the verdict timestamp persists across
        # process boots, where no monotonic clock is comparable
        if time.time() - float(data.get("time", 0)) > ttl_s:  # pilosa: allow(wall-clock)
            return None
        if not isinstance(data.get("ok"), bool):
            return None
        return data
    except Exception:  # noqa: BLE001 — missing/corrupt cache = no verdict
        return None


def store(ok: bool, pin: str = "", platform: str = "") -> None:
    path = cache_path()
    try:
        from pilosa_tpu.utils import durable

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # durable=False: atomic replace without the fsyncs — a probe
        # verdict lost to a crash just costs one fresh probe
        durable.atomic_write_file(
            path,
            json.dumps(
                {
                    "ok": bool(ok),
                    "pin": pin or "",
                    "platform": platform,
                    "time": time.time(),
                }
            ),
            tmp_suffix=f".tmp.{os.getpid()}",
            durable=False,
        )
    except Exception:  # noqa: BLE001 — persistence is best-effort
        pass
