"""Workload intelligence: continuous query capture, heavy-hitter
analysis, SLO burn-rate tracking, and capture→replay benching.

PR 1/PR 10 made INDIVIDUAL queries observable (traces, profiles, the
flight recorder); this module is the aggregate half — what the fleet of
queries looks like (docs/workload.md).  Analytics systems are
characterized by their operator mix and data-reuse profile (PIMDAL,
arXiv 2504.01948), and the ROADMAP's next perf levers (cross-query
result cache, wire-speed ingest, multi-process serving) are all sized
by claims about traffic shape — so the serving path measures its own
workload continuously instead of assuming:

- **Continuous capture** — every settled public query contributes one
  compact normalized record: a *fingerprint* (canonicalized PQL call
  tree + index + shard set — whitespace/keyword-order independent, so
  "the same segmentation query" hashes identically however a client
  formats it), the raw PQL, route, latency, result bytes, status, and
  trace id.  Records land in a bounded in-memory ring (sampled past
  ``workload-sample-rate``) with optional durable spill to size/age-
  bounded JSONL segments (``workload-capture-path``, written through
  ``utils/durable.py``).
- **Heavy-hitter analysis** — a SpaceSaving (Misra-Gries family) top-K
  sketch over fingerprints, with per-fingerprint latency/churn stats.
  The churn half feeds the *cachability estimate*: a repeat of a
  fingerprint whose mutation stamp (the same view-version stack token
  single-flight dedup keys on, executor/scheduler.py) is UNCHANGED is
  exactly a query a mutation-stamped result cache (ROADMAP item 2)
  would have served from cache — ``GET /debug/workload`` reports the
  QPS such a cache would have absorbed, measured, not assumed.
- **SLO engine** — per-call-type objectives (``slo-targets`` grammar:
  ``count:p95<50ms:99.9``) tracked as multi-window burn rates (5m/1h
  bucketed windows), exposed as ``slo_burn_rate{call,window}`` /
  ``slo_budget_remaining{call}`` gauges and ``GET /debug/slo`` — a
  burn rate over 1.0 spends error budget faster than the objective
  allows, alertable before users notice.
- **Capture→replay** — ``pilosa_tpu replay <capture>`` replays a
  captured workload against a live server preserving recorded arrival
  spacing (or scaled: ``--speed``/``--qps``/``--closed-loop``),
  reporting QPS/p50/p95/error rate and the divergence count vs the
  recorded statuses; ``make bench-workload`` gates capture overhead
  and replay fidelity on the config8 mix.

Steady-state cost per query: one cached-dict fingerprint lookup, one
sketch offer, one histogram observe, and (sampled) one ring append —
the ``bench-workload`` gate holds the whole plane at ≤3% c1 p50.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable

from pilosa_tpu.utils import sanitize
from pilosa_tpu.utils.stats import Histogram

# ring records keep the raw PQL truncated to this many characters —
# enough to replay every realistic query, bounded against a pathological
# megabyte query body ballooning the ring
_MAX_PQL = 2000
# fingerprint cache: raw (index, pql, shards) → fingerprint; cleared
# wholesale when full (the route-cache idiom — repeated traffic is the
# point of this plane, so the steady state is all hits)
_FP_CACHE_MAX = 4096


# ------------------------------------------------------------ fingerprint
def _render(v: Any) -> str:
    from pilosa_tpu.pql.ast import Call, Condition, _render_value

    if isinstance(v, Call):
        return _canon_call(v)
    if isinstance(v, Condition):
        if v.op == "between":
            lo, hi = v.value
            return f"between[{_render(lo)},{_render(hi)}]"
        return f"{v.op}{_render(v.value)}"
    return _render_value(v)


def _canon_call(call) -> str:
    """Canonical text of one PQL call: children and positional args in
    place (operand order is semantics for Difference/Shift and harmless
    elsewhere), keyword args SORTED by name — ``Row(f=1)`` and a
    client that spells its options in another order fingerprint
    identically.  Whitespace never survives (this renders from the
    AST, not the source text)."""
    parts = [_canon_call(c) for c in call.children]
    parts += [_render(v) for v in call.pos_args]
    for k in sorted(call.args):
        parts.append(f"{k}={_render(call.args[k])}")
    return f"{call.name}({','.join(parts)})"


class Fingerprinter:
    """Query → stable 16-hex-char workload fingerprint.

    The fingerprint identifies "the same query against the same data
    scope": canonicalized call tree + index + explicit shard set.  Row
    values and call arguments are PART of the identity — the heavy-
    hitter report and the result-cache sizing both need ``Count(Row(
    cab=1))`` and ``Count(Row(cab=2))`` to be different queries.
    Lookups are cached on the RAW (index, pql, shards) key so the hot
    path pays a dict hit, not a parse."""

    def __init__(self):
        self._lock = sanitize.make_lock("Fingerprinter._lock", loop_safe=True)
        self._cache: dict[tuple, tuple[str, str]] = {}

    def fingerprint(
        self, index: str, pql, shards: list[int] | None
    ) -> tuple[str, str]:
        """(fingerprint, call_type) for one query.  ``pql`` is the raw
        string (HTTP path) or an already-parsed call list."""
        shard_key = tuple(sorted(set(shards))) if shards else None
        raw_key = None
        if isinstance(pql, str):
            raw_key = (index, pql, shard_key)
            with self._lock:
                hit = self._cache.get(raw_key)
            if hit is not None:
                return hit
        try:
            from pilosa_tpu.pql import parse

            calls = parse(pql) if isinstance(pql, str) else pql
            canon = " ".join(_canon_call(c) for c in calls)
            call_type = calls[0].name if calls else "?"
        except Exception:  # noqa: BLE001 — an unparseable query still
            # deserves a stable identity (it shows up as an errored
            # heavy hitter); fall back to the raw text
            canon = pql if isinstance(pql, str) else repr(pql)
            call_type = str(canon).split("(", 1)[0].strip()[:32] or "?"
        scope = "all" if shard_key is None else ",".join(map(str, shard_key))
        digest = hashlib.blake2b(
            f"{index}|{scope}|{canon}".encode(), digest_size=8
        ).hexdigest()
        out = (digest, call_type)
        if raw_key is not None:
            with self._lock:
                if len(self._cache) >= _FP_CACHE_MAX:
                    self._cache.clear()
                self._cache[raw_key] = out
        return out


# ------------------------------------------------------- top-K sketch
class SpaceSaving:
    """SpaceSaving top-K heavy-hitter sketch (Metwally et al.; the
    Misra-Gries family): at most ``k`` counters; an unseen key past
    capacity REPLACES the minimum counter and inherits its count as
    overestimation error.  Guarantees: every true count is within
    [estimate - error, estimate], and any key with true frequency
    above N/k is tracked — exactly the shape needed for "which
    fingerprints dominate the workload" without unbounded state."""

    def __init__(self, k: int = 64):
        self.k = max(1, int(k))
        self._lock = sanitize.make_lock("SpaceSaving._lock", loop_safe=True)
        # key -> [count, error]
        self._counters: dict[str, list[int]] = {}
        self.observed = 0

    def offer(self, key: str, inc: int = 1) -> str | None:
        """Count one observation; returns the key EVICTED to make room
        (the caller drops its per-key stats), or None."""
        with self._lock:
            self.observed += inc
            c = self._counters.get(key)
            if c is not None:
                c[0] += inc
                return None
            if len(self._counters) < self.k:
                self._counters[key] = [inc, 0]
                return None
            victim = min(self._counters, key=lambda x: self._counters[x][0])
            floor = self._counters.pop(victim)[0]
            self._counters[key] = [floor + inc, floor]
            return victim

    def top(self, n: int | None = None) -> list[tuple[str, int, int]]:
        """[(key, estimated_count, max_overestimate)] sorted by count
        descending."""
        with self._lock:
            items = sorted(
                self._counters.items(), key=lambda kv: -kv[1][0]
            )
        out = [(k, c[0], c[1]) for k, c in items]
        return out[: n] if n else out

    def rank(self, key: str) -> int | None:
        """1-based heavy-hitter rank of ``key``, or None if untracked."""
        for i, (k, _c, _e) in enumerate(self.top()):
            if k == key:
                return i + 1
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._counters)


class _FpStats:
    """Per-fingerprint aggregate, kept only while the sketch tracks the
    fingerprint (bounded by top-K)."""

    __slots__ = (
        "index", "call", "example", "count", "errors", "bytes_total",
        "hist", "last_stamp", "unchanged_repeats", "cache_hits",
    )

    def __init__(self, index: str, call: str, example: str):
        self.index = index
        self.call = call
        self.example = example
        self.count = 0
        self.errors = 0
        self.bytes_total = 0
        self.hist = Histogram()
        self.last_stamp = None
        self.unchanged_repeats = 0
        self.cache_hits = 0

    def observe(
        self, seconds: float, nbytes: int, error: bool, stamp,
        byte_cap: int = 0,
    ) -> None:
        if (
            self.count > 0
            and stamp is not None
            and stamp == self.last_stamp
            and not (byte_cap > 0 and nbytes > byte_cap)
        ):
            # a repeat under an unchanged mutation stamp: the query a
            # stamped result cache would have served from cache.
            # Results over the cache's per-entry byte cap are excluded
            # — they would never be admitted, and counting them
            # overstated servable QPS for giant results
            self.unchanged_repeats += 1
        self.last_stamp = stamp
        self.count += 1
        if error:
            self.errors += 1
        self.bytes_total += int(nbytes)
        self.hist.observe(seconds)

    def to_json(self) -> dict:
        snap = self.hist.snapshot()
        return {
            "index": self.index,
            "call": self.call,
            "examplePql": self.example,
            "observed": self.count,
            "errors": self.errors,
            "resultBytesTotal": self.bytes_total,
            "meanMs": round(
                snap["totalSeconds"] / max(1, snap["count"]) * 1e3, 3
            ),
            "p95Ms": round(snap["p95"] * 1e3, 3),
            "repeats": max(0, self.count - 1),
            "repeatsUnchangedStamp": self.unchanged_repeats,
            # MEASURED cache serves vs the estimate above — estimator
            # drift reads directly off this pair (docs/result-cache.md)
            "cacheHits": self.cache_hits,
            "actualHitFraction": round(
                self.cache_hits / max(1, self.count), 4
            ),
            "stampChurn": round(
                1.0
                - self.unchanged_repeats / max(1, self.count - 1), 4
            ) if self.count > 1 else None,
        }


# ------------------------------------------------------------ SLO engine
_SLO_LAT_RE = re.compile(r"^p(\d{1,2})<(\d+(?:\.\d+)?)(ms|s)$")
# gauges republish at most this often — burn-rate math is a ~60-bucket
# scan and must not run per query on the hot path
_GAUGE_REPUBLISH_S = 1.0
WINDOWS = (("5m", 300.0, 30), ("1h", 3600.0, 60))
# distinct call types a WILDCARD target may track: call_type is derived
# from client-controlled PQL (unparseable queries fall back to raw
# text), so without a cap a garbage-spraying client would mint one
# permanent window pair + slo_burn_rate series per distinct string —
# unbounded memory and metric cardinality.  Explicitly-named targets
# are bounded by config and always tracked.
_MAX_SLO_CALLS = 64


class SLOTarget:
    """One parsed ``slo-targets`` entry — TWO objectives per target:

    ``<call>:p95<50ms:99.9`` — a latency quantile objective (the p95
    must sit under 50ms, i.e. at most 5% of queries may exceed it —
    the percentile IS the latency error budget) plus an availability
    objective (99.9% of queries must not error).  ``<call>:errors:
    99.9`` tracks availability only.  ``call`` matches the query's
    first call name case-insensitively; ``*`` matches any."""

    __slots__ = ("call", "threshold_s", "quantile", "objective", "spec")

    def __init__(self, spec: str):
        parts = spec.strip().split(":")
        if len(parts) != 3:
            raise ValueError(
                f"bad slo target {spec!r}: want <call>:<p95<50ms|errors>:"
                "<objective-pct>"
            )
        self.spec = spec.strip()
        self.call = parts[0].strip().lower()
        cond = parts[1].strip().lower()
        if cond in ("errors", "avail", "availability"):
            self.threshold_s = None
            self.quantile = None
        else:
            m = _SLO_LAT_RE.match(cond)
            if m is None:
                raise ValueError(
                    f"bad slo condition {parts[1]!r}: want pNN<MMms (or "
                    "'errors' for availability-only)"
                )
            scale = 1e-3 if m.group(3) == "ms" else 1.0
            self.threshold_s = float(m.group(2)) * scale
            q = float(m.group(1))
            if not 0.0 < q < 100.0:
                raise ValueError(
                    f"slo latency quantile must be in p1..p99, got p{m.group(1)}"
                )
            self.quantile = q / 100.0
        obj = float(parts[2])
        if not 0.0 < obj < 100.0:
            raise ValueError(
                f"slo objective must be in (0, 100), got {parts[2]!r}"
            )
        self.objective = obj / 100.0

    @property
    def avail_budget(self) -> float:
        """Allowed errored fraction (the availability error budget)."""
        return 1.0 - self.objective

    @property
    def latency_budget(self) -> float | None:
        """Allowed over-threshold fraction — 1 − quantile (5% for a
        p95 target), None for availability-only targets."""
        return None if self.quantile is None else 1.0 - self.quantile


def parse_slo_targets(raw: str) -> list[SLOTarget]:
    out = []
    for spec in re.split(r"[,;]", raw or ""):
        if spec.strip():
            out.append(SLOTarget(spec))
    return out


class _BucketWindow:
    """Total / over-threshold / errored counts over a rolling window of
    fixed-width buckets.  Buckets are addressed by ``clock() //
    bucket_s`` so stale slots self-invalidate lazily — no sweeper
    thread, O(1) add, O(buckets) read."""

    __slots__ = ("span_s", "n", "bucket_s", "total", "slow", "err", "epoch")

    def __init__(self, span_s: float, n: int):
        self.span_s = span_s
        self.n = n
        self.bucket_s = span_s / n
        self.total = [0] * n
        self.slow = [0] * n
        self.err = [0] * n
        self.epoch = [-1] * n

    def _slot(self, now: float) -> int:
        b = int(now // self.bucket_s)
        i = b % self.n
        if self.epoch[i] != b:
            self.epoch[i] = b
            self.total[i] = 0
            self.slow[i] = 0
            self.err[i] = 0
        return i

    def add(self, now: float, slow: bool, error: bool) -> None:
        i = self._slot(now)
        self.total[i] += 1
        if slow:
            self.slow[i] += 1
        if error:
            self.err[i] += 1

    def totals(self, now: float) -> tuple[int, int, int]:
        """(total, over_threshold, errored) within the window ending at
        ``now``."""
        cur = int(now // self.bucket_s)
        t = s = e = 0
        for i in range(self.n):
            if cur - self.epoch[i] < self.n:
                t += self.total[i]
                s += self.slow[i]
                e += self.err[i]
        return t, s, e


class SLOEngine:
    """Per-call-type SLO burn rates over multiple windows.

    ``observe`` classifies each settled query against BOTH of its call
    type's objectives — over-threshold (latency) and errored
    (availability) — and feeds every window.  Each objective burns its
    own budget: latency burn = over-threshold fraction / (1 −
    quantile), availability burn = errored fraction / (1 − objective);
    the reported burn rate is the MAX of the two — the binding
    constraint.  1.0 = spending exactly that budget, >1.0 = the
    objective will be missed if sustained (page-worthy at ~14x on the
    5m window per the standard multi-window alerting recipe).  Budget
    remaining is reported over the LONGEST window."""

    def __init__(
        self,
        targets: "list[SLOTarget] | str" = "",
        stats=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if isinstance(targets, str):
            targets = parse_slo_targets(targets)
        self.targets = targets
        self.stats = stats
        self._clock = clock
        self._lock = sanitize.make_lock("SLOEngine._lock", loop_safe=True)
        # call (lowercased) -> target; "*" is the fallback
        self._by_call = {t.call: t for t in targets}
        # call -> {window_name: _BucketWindow}
        self._windows: dict[str, dict[str, _BucketWindow]] = {}
        self._last_publish = 0.0

    @property
    def enabled(self) -> bool:
        return bool(self.targets)

    def target_for(self, call_type: str) -> SLOTarget | None:
        return self._by_call.get(call_type.lower()) or self._by_call.get("*")

    def observe(self, call_type: str, seconds: float, error: bool) -> None:
        t = self.target_for(call_type)
        if t is None:
            return
        slow = t.threshold_s is not None and seconds > t.threshold_s
        now = self._clock()
        key = call_type.lower()
        with self._lock:
            wins = self._windows.get(key)
            if wins is None:
                if (
                    key not in self._by_call
                    and len(self._windows) >= _MAX_SLO_CALLS
                ):
                    # a wildcard-matched call type past the cardinality
                    # cap: drop rather than mint another permanent
                    # window pair + gauge series for client-controlled
                    # input (explicit targets always track)
                    return
                wins = self._windows[key] = {
                    name: _BucketWindow(span, n) for name, span, n in WINDOWS
                }
            for w in wins.values():
                w.add(now, slow, error)
            publish = (
                self.stats is not None
                and now - self._last_publish >= _GAUGE_REPUBLISH_S
            )
            if publish:
                self._last_publish = now
        if publish:
            self.publish_gauges()

    @staticmethod
    def _burn(t: "SLOTarget | None", total: int, slow: int, err: int) -> dict:
        """Both burn components plus the binding max for one window."""
        if t is None or total == 0:
            return {"latency": 0.0, "availability": 0.0, "max": 0.0}
        avail = (err / total) / t.avail_budget
        lat = (
            (slow / total) / t.latency_budget
            if t.latency_budget is not None
            else 0.0
        )
        return {"latency": lat, "availability": avail, "max": max(lat, avail)}

    def burn_rates(self, call: str) -> dict:
        """{window: burn_rate} for one call type (0.0 when idle); the
        rate is the max over the latency and availability components —
        the binding constraint."""
        t = self.target_for(call)
        now = self._clock()
        out = {}
        with self._lock:
            wins = self._windows.get(call.lower(), {})
            for name, _span, _n in WINDOWS:
                w = wins.get(name)
                if w is None:
                    out[name] = 0.0
                    continue
                total, slow, err = w.totals(now)
                out[name] = self._burn(t, total, slow, err)["max"]
        return out

    def budget_remaining(self, call: str) -> float:
        """Fraction of the error budget left over the longest window
        (negative once overspent)."""
        rates = self.burn_rates(call)
        longest = WINDOWS[-1][0]
        return 1.0 - rates.get(longest, 0.0)

    def publish_gauges(self) -> None:
        if self.stats is None:
            return
        for call in list(self._windows):
            rates = self.burn_rates(call)
            for window, rate in rates.items():
                self.stats.gauge(
                    "slo_burn_rate",
                    round(rate, 6),
                    tags={"call": call, "window": window},
                )
            self.stats.gauge(
                "slo_budget_remaining",
                round(self.budget_remaining(call), 6),
                tags={"call": call},
            )

    def snapshot(self) -> dict:
        """The ``GET /debug/slo`` report."""
        now = self._clock()
        calls: dict[str, dict] = {}
        with self._lock:
            tracked = {
                c: dict(wins) for c, wins in self._windows.items()
            }
        for call, wins in tracked.items():
            t = self.target_for(call)
            per_window = {}
            for name, _span, _n in WINDOWS:
                w = wins.get(name)
                total, slow, err = (
                    w.totals(now) if w is not None else (0, 0, 0)
                )
                burn = self._burn(t, total, slow, err)
                per_window[name] = {
                    "total": total,
                    "overThreshold": slow,
                    "errors": err,
                    "latencyBurnRate": round(burn["latency"], 4),
                    "availabilityBurnRate": round(burn["availability"], 4),
                    "burnRate": round(burn["max"], 4),
                }
            calls[call] = {
                "target": t.spec if t is not None else None,
                "objectivePct": round(t.objective * 100, 4)
                if t is not None else None,
                "latencyQuantile": (
                    round(t.quantile * 100, 2)
                    if t is not None and t.quantile is not None
                    else None
                ),
                "latencyThresholdMs": (
                    round(t.threshold_s * 1e3, 3)
                    if t is not None and t.threshold_s is not None
                    else None
                ),
                "windows": per_window,
                "budgetRemaining": round(self.budget_remaining(call), 4),
            }
        return {
            "enabled": self.enabled,
            "targets": [t.spec for t in self.targets],
            "windows": {name: span for name, span, _n in WINDOWS},
            "calls": calls,
        }


# --------------------------------------------------------------- capture
class WorkloadPlane:
    """The always-on workload-intelligence plane: one per serving front
    end, fed by the HTTP layer at every public query's settle point
    (``record``).  Owns the fingerprint cache, the heavy-hitter sketch
    + per-fingerprint stats, the sampled capture ring with optional
    durable spill, and the SLO engine."""

    def __init__(
        self,
        enabled: bool = True,
        capacity: int = 4096,
        sample_rate: float = 1.0,
        top_k: int = 64,
        capture_path: str | None = None,
        spill_max_bytes: int = 4_000_000,
        spill_max_age_s: float = 60.0,
        spill_segments: int = 8,
        slo_targets: "str | list[SLOTarget]" = "",
        stats=None,
        log: Callable[[str], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.enabled = bool(enabled)
        self.capacity = max(1, int(capacity))
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        # deterministic modulo sampling: every Nth query lands in the
        # ring/spill (sketch + SLO always observe).  Deliberately not
        # randomized — replays want contiguous slices of traffic, and a
        # strictly periodic client would alias identically either way.
        # N = ceil(1/rate): the EFFECTIVE rate (1/N, reported in
        # vars_snapshot) never exceeds the configured one — round()
        # would silently sample 100% for any rate above 2/3.
        self._sample_every = (
            math.ceil(1.0 / self.sample_rate) if self.sample_rate > 0 else 0
        )
        self.capture_path = capture_path or None
        self.spill_max_bytes = int(spill_max_bytes)
        self.spill_max_age_s = float(spill_max_age_s)
        self.spill_segments = max(1, int(spill_segments))
        self.stats = stats
        self.log = log
        self._clock = clock
        self.fingerprints = Fingerprinter()
        self.sketch = SpaceSaving(top_k)
        # the result cache's per-entry byte cap (wired by Server.open):
        # repeats whose results exceed it are NOT servable and must not
        # inflate the cachability estimate; 0 = no cap known
        self.cache_byte_cap = 0
        # aggregate measured cache serves (per-fingerprint counts live
        # on _FpStats; this counts hits for evicted/untracked fps too)
        self.cache_hits = 0
        self.slo = SLOEngine(slo_targets, stats=stats, clock=clock)
        self._lock = sanitize.make_lock("WorkloadPlane._lock", loop_safe=True)
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._fp_stats: dict[str, _FpStats] = {}
        self.observed = 0
        self.sampled = 0
        self.dropped = 0  # observed but not ring-sampled
        self._started = clock()
        # spill state: records buffer + segment bookkeeping.  A restart
        # RESUMES the segment sequence (scanning the capture dir) so a
        # fresh process never overwrites the previous run's segments,
        # and pre-existing segments count against the retention cap.
        self._spill_buf: list[str] = []
        self._spill_bytes = 0
        self._spill_opened = clock()
        self._spill_seq = 0
        self._spill_paths: deque[str] = deque()
        if self.capture_path is not None:
            try:
                existing = sorted(
                    f
                    for f in os.listdir(self.capture_path)
                    if re.fullmatch(r"workload-\d+\.jsonl", f)
                )
            except OSError:
                existing = []
            for f in existing:
                self._spill_paths.append(
                    os.path.join(self.capture_path, f)
                )
            if existing:
                self._spill_seq = int(existing[-1][len("workload-"):-len(".jsonl")])

    # ------------------------------------------------------------ intake
    def fingerprint(
        self, index: str, pql, shards: list[int] | None
    ) -> tuple[str, str]:
        return self.fingerprints.fingerprint(index, pql, shards)

    def rank(self, fp: str) -> int | None:
        return self.sketch.rank(fp)

    def record(
        self,
        index: str,
        pql: str,
        fp: str,
        call_type: str,
        seconds: float,
        status: int,
        nbytes: int,
        route: str | None = None,
        trace_id: str | None = None,
        stamp=None,
        arrival: float | None = None,
        shards: list[int] | None = None,
        spill: bool = True,
    ) -> None:
        """One settled public query.  ``stamp`` is the index's current
        view-version mutation stamp (API.mutation_stamp) — the
        cachability signal; ``arrival`` the request's arrival monotonic
        time (event front end), so replay spacing reflects offered
        load, not completion times; ``shards`` the request's explicit
        shard scope (part of the fingerprint identity — replay must
        re-issue the same scope, not an all-shards variant).
        ``spill=False`` skips the durable spill file alone (the event
        loop settles cache hits on the loop thread, where file I/O has
        no place); the ring, sketch, and stats always observe."""
        if not self.enabled:
            return
        error = status >= 400
        self.slo.observe(call_type, seconds, error)
        with self._lock:
            self.observed += 1
            n = self.observed
            # offer + stats maintenance are ONE atomic step under the
            # plane lock (the sketch's own lock nests inside — same
            # order everywhere): two settles racing eviction could
            # otherwise install stats for an already-evicted key,
            # leaking entries until the bound blocked all new stats
            evicted = self.sketch.offer(fp)
            if evicted is not None:
                self._fp_stats.pop(evicted, None)
            st = self._fp_stats.get(fp)
            if st is None:
                st = self._fp_stats[fp] = _FpStats(
                    index, call_type, pql[:_MAX_PQL]
                )
            st.observe(
                seconds, nbytes, error, stamp,
                byte_cap=self.cache_byte_cap,
            )
            take = self._sample_every > 0 and (n % self._sample_every == 0)
            if not take:
                self.dropped += 1
                rec = None
            else:
                self.sampled += 1
                rec = {
                    "t": round(
                        arrival if arrival is not None else self._clock(), 6
                    ),
                    "fp": fp,
                    "index": index,
                    "call": call_type,
                    "pql": pql[:_MAX_PQL],
                    "route": route,
                    "latencyS": round(seconds, 6),
                    "bytes": int(nbytes),
                    "status": int(status),
                    "traceId": trace_id,
                }
                if shards:
                    rec["shards"] = sorted(set(shards))
                self._ring.append(rec)
        if self.stats is not None:
            self.stats.count("workload_observed_total")
            if rec is not None:
                self.stats.count("workload_sampled_total")
        if rec is not None and spill and self.capture_path is not None:
            self._spill(rec)

    def record_cache_hit(self, fp: str) -> None:
        """One result-cache serve for this fingerprint — the MEASURED
        half of the estimate-vs-actual pair /debug/workload reports
        (``servableFraction`` vs ``actualHitFraction``)."""
        if not self.enabled:
            return
        # loop_safe: two counter bumps, no I/O under the lock;
        # registered loop_safe with the sanitizer (make_lock)
        with self._lock:  # pilosa: allow(loop-purity)
            self.cache_hits += 1
            st = self._fp_stats.get(fp)
            if st is not None:
                st.cache_hits += 1

    # -------------------------------------------------------------- spill
    def _spill(self, rec: dict) -> None:
        """Buffer one record; cut a segment when the buffer exceeds the
        size bound or the open segment exceeds the age bound.  Segments
        are whole JSONL files written atomically (utils/durable.py,
        best-effort — capture loss must never cost a query), oldest
        deleted past ``spill_segments``."""
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        flush = False
        with self._lock:
            if not self._spill_buf:
                # age is measured from the FIRST buffered record, not
                # the last flush — otherwise the first record after an
                # idle gap would instantly cut a one-record segment and
                # erode the retention window.  The age cut itself is
                # evaluated at record time (no timer thread): an idle
                # server's buffered tail flushes at close(), documented
                # as the capture's best-effort contract.
                self._spill_opened = self._clock()
            self._spill_buf.append(line)
            self._spill_bytes += len(line)
            age = self._clock() - self._spill_opened
            if (
                self._spill_bytes >= self.spill_max_bytes
                or age >= self.spill_max_age_s
            ):
                flush = True
        if flush:
            self.flush_spill()

    def flush_spill(self) -> None:
        """Cut the open spill segment (also called at close)."""
        if self.capture_path is None:
            return
        from pilosa_tpu.utils import durable

        with self._lock:
            if not self._spill_buf:
                return
            body = "".join(self._spill_buf)
            self._spill_buf = []
            self._spill_bytes = 0
            self._spill_opened = self._clock()
            self._spill_seq += 1
            seq = self._spill_seq
        try:
            os.makedirs(self.capture_path, exist_ok=True)
            path = os.path.join(
                self.capture_path, f"workload-{seq:06d}.jsonl"
            )
            durable.atomic_write_file(
                path, body, op="workload-spill", durable=False
            )
            drops = []
            with self._lock:
                self._spill_paths.append(path)
                while len(self._spill_paths) > self.spill_segments:
                    drops.append(self._spill_paths.popleft())
                if self.stats is not None:
                    self.stats.gauge(
                        "workload_spill_segments",
                        float(len(self._spill_paths)),
                    )
            for drop in drops:
                os.remove(drop)
        except OSError as e:
            if self.log is not None:
                self.log(f"workload spill failed (capture lost): {e}")

    def close(self) -> None:
        self.flush_spill()

    # ------------------------------------------------------------ surface
    def capture_records(self) -> list[dict]:
        """The ring's records, oldest first (the ``format=capture``
        export replay consumes)."""
        with self._lock:
            return list(self._ring)

    def report(self, top: int = 20) -> dict:
        """The ``GET /debug/workload`` report: top-K heavy hitters with
        per-fingerprint stats and the cachability estimate."""
        now = self._clock()
        elapsed = max(1e-9, now - self._started)
        with self._lock:
            observed = self.observed
            fp_stats = dict(self._fp_stats)
            cache_hits = self.cache_hits
        entries = []
        servable = 0
        tracked_observed = 0
        tracked_hits = 0
        for i, (fp, count, err) in enumerate(self.sketch.top(top)):
            st = fp_stats.get(fp)
            entry = {
                "rank": i + 1,
                "fingerprint": fp,
                "estimatedCount": count,
                "maxOverestimate": err,
            }
            if st is not None:
                entry.update(st.to_json())
            entries.append(entry)
        for st in fp_stats.values():
            servable += st.unchanged_repeats
            tracked_observed += st.count
            tracked_hits += st.cache_hits
        return {
            "enabled": self.enabled,
            "observed": observed,
            "distinctTracked": len(self.sketch),
            "sketchK": self.sketch.k,
            "windowSeconds": round(elapsed, 3),
            "topK": entries,
            # what the ROADMAP-item-2 mutation-stamped result cache
            # would have served from cache, measured from observed
            # repeats whose view-version stamp was unchanged
            "cachability": {
                "servableRepeats": servable,
                "trackedObserved": tracked_observed,
                "servableFraction": round(
                    servable / max(1, tracked_observed), 4
                ),
                "servableQps": round(servable / elapsed, 3),
                # the MEASURED result-cache serves next to the estimate
                # above — estimator drift is the gap between these
                # (docs/result-cache.md); actualHits counts every hit,
                # actualHitFraction only tracked fingerprints so it is
                # comparable to servableFraction
                "actualHits": cache_hits,
                "actualHitFraction": round(
                    tracked_hits / max(1, tracked_observed), 4
                ),
                "cacheByteCap": self.cache_byte_cap or None,
            },
            "slo": {"enabled": self.slo.enabled},
        }

    def vars_snapshot(self) -> dict:
        """The /debug/vars ``workload`` section (capture-plane health;
        the analysis itself lives at /debug/workload)."""
        with self._lock:
            ring_depth = len(self._ring)
            observed = self.observed
            sampled = self.sampled
            dropped = self.dropped
            spill_segments = len(self._spill_paths)
            spill_pending = len(self._spill_buf)
        if self.stats is not None:
            self.stats.gauge(
                "workload_fingerprints_tracked", float(len(self.sketch))
            )
        return {
            "enabled": self.enabled,
            "captureRingDepth": ring_depth,
            "captureRingCapacity": self.capacity,
            "observed": observed,
            "sampled": sampled,
            "dropped": dropped,
            "sampleRate": self.sample_rate,
            # 1/N after the every-Nth quantization — what the ring
            # actually receives (never above the configured rate)
            "effectiveSampleRate": (
                1.0 / self._sample_every if self._sample_every else 0.0
            ),
            "sketchSize": len(self.sketch),
            "sketchK": self.sketch.k,
            "spillPath": self.capture_path,
            "spillSegments": spill_segments,
            "spillPendingRecords": spill_pending,
            "sloEnabled": self.slo.enabled,
        }


# ---------------------------------------------------------------- replay
def load_capture(path: str) -> list[dict]:
    """Capture records from one JSONL file or a directory of spill
    segments.  Records sort by arrival time WITHIN each file (settle
    order can lag arrival order under concurrency); files concatenate
    in segment-sequence order, never by timestamp — ``t`` is a
    monotonic stamp that restarts with the process, so a capture
    directory spanning a server restart must keep its boot-local
    timelines in segment order (replay clamps the negative jump at the
    boundary to a zero gap)."""
    paths = [path]
    if os.path.isdir(path):
        paths = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(".jsonl")
        )
        if not paths:
            raise ValueError(f"no .jsonl capture segments under {path!r}")
    records = []
    for p in paths:
        with open(p) as f:
            chunk = [json.loads(ln) for ln in f if ln.strip()]
        chunk.sort(key=lambda r: r.get("t", 0.0))
        records.extend(chunk)
    if not records:
        raise ValueError(f"capture {path!r} holds no records")
    return records


def _arrival_gaps(records: list[dict]) -> list[float]:
    """Inter-arrival gaps with negative jumps (a server-restart
    boundary between monotonic timelines) clamped to zero."""
    out = [0.0]
    for prev, cur in zip(records, records[1:]):
        out.append(max(0.0, cur.get("t", 0.0) - prev.get("t", 0.0)))
    return out


def recorded_summary(records: list[dict]) -> dict:
    """Per-call-type recorded counts/QPS/latency from a capture — the
    reference half of the fidelity comparison."""
    span = max(1e-9, sum(_arrival_gaps(records)))
    per_call: dict[str, dict] = {}
    for r in records:
        c = per_call.setdefault(
            r.get("call", "?"),
            {"sent": 0, "errors": 0, "hist": Histogram()},
        )
        c["sent"] += 1
        if r.get("status", 200) >= 400:
            c["errors"] += 1
        c["hist"].observe(float(r.get("latencyS", 0.0)))
    out = {}
    for call, c in per_call.items():
        out[call] = {
            "sent": c["sent"],
            "share": round(c["sent"] / len(records), 4),
            "qps": round(c["sent"] / span, 3),
            "p50Ms": round(c["hist"].percentile(0.5) * 1e3, 3),
            "p95Ms": round(c["hist"].percentile(0.95) * 1e3, 3),
            "errors": c["errors"],
        }
    return {"records": len(records), "spanSeconds": round(span, 3),
            "perCall": out}


class _ReplayClient:
    """One keep-alive connection per replay worker thread."""

    def __init__(self, base_uri: str, timeout: float, ssl_context=None):
        import http.client
        from urllib.parse import urlsplit

        u = urlsplit(base_uri if "//" in base_uri else f"http://{base_uri}")
        if u.scheme == "https":
            # the caller's context carries --tls-skip-verify; default
            # verification otherwise
            self._make = lambda: http.client.HTTPSConnection(
                u.hostname, u.port, timeout=timeout, context=ssl_context
            )
        else:
            self._make = lambda: http.client.HTTPConnection(
                u.hostname, u.port, timeout=timeout
            )
        self._conn = self._make()

    def query(self, index: str, pql: str, shards=None) -> int:
        import http.client

        path = f"/index/{index}/query"
        if shards:
            path += "?shards=" + ",".join(map(str, shards))
        for attempt in (0, 1):
            try:
                self._conn.request("POST", path, pql.encode())
                resp = self._conn.getresponse()
                resp.read()
                return resp.status
            except (OSError, http.client.HTTPException):
                # one transparent redial: the server's keep-alive idle
                # reap between bursts is not a replay failure.
                # HTTPException too (BadStatusLine from a non-HTTP
                # endpoint) — it must surface as a transport failure,
                # not kill the worker thread
                self._conn.close()
                self._conn = self._make()
                if attempt:
                    raise
        return 0  # pragma: no cover — loop always returns/raises

    def close(self) -> None:
        self._conn.close()


def replay(
    records: list[dict],
    base_uri: str,
    speed: float = 1.0,
    qps: float | None = None,
    closed_loop: int | None = None,
    workers: int = 8,
    timeout: float = 30.0,
    ssl_context=None,
) -> dict:
    """Replay a captured workload against a live server.

    Pacing modes (docs/workload.md):
    - default: recorded arrival spacing, scaled by ``speed``;
    - ``qps``: uniform arrivals at a fixed rate;
    - ``closed_loop``: N clients issue back-to-back (throughput mode —
      spacing is discarded).

    Open-loop arrivals are served by a worker pool so one slow reply
    cannot stall the offered load behind it.  Returns a bench-row-
    shaped report: QPS, p50/p95, error rate, and the DIVERGENCE count —
    replayed queries whose HTTP status differed from the recorded one
    (a replay against drifted data or a broken build shows up here,
    not as a silently different bench number)."""
    if not records:
        raise ValueError("empty capture")
    if closed_loop:
        n_workers = max(1, int(closed_loop))
        due = None
    else:
        n_workers = max(1, min(int(workers), len(records)))
        if qps:
            due = [i / float(qps) for i in range(len(records))]
        else:
            sp = max(1e-6, float(speed))
            due, acc = [], 0.0
            for gap in _arrival_gaps(records):
                acc += gap / sp
                due.append(acc)

    lock = threading.Lock()
    next_i = [0]
    results: list[tuple[str, float, int, int]] = []  # call, lat, status, rec
    failures: list[str] = []
    start = time.monotonic()

    def run_one(client: _ReplayClient, rec: dict) -> None:
        import http.client

        t1 = time.perf_counter()
        try:
            status = client.query(
                rec.get("index", ""), rec.get("pql", ""),
                rec.get("shards"),
            )
        except (OSError, http.client.HTTPException) as e:
            with lock:
                failures.append(f"{type(e).__name__}: {e}")
            return
        lat = time.perf_counter() - t1
        with lock:
            results.append(
                (rec.get("call", "?"), lat, status,
                 int(rec.get("status", 200)))
            )

    def worker() -> None:
        client = _ReplayClient(base_uri, timeout, ssl_context)
        try:
            while True:
                with lock:
                    i = next_i[0]
                    if i >= len(records):
                        return
                    next_i[0] += 1
                if due is not None:
                    delay = start + due[i] - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                run_one(client, records[i])
        finally:
            client.close()

    threads = [
        threading.Thread(target=worker, daemon=True, name=f"replay-worker-{i}")
        for i in range(n_workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = max(1e-9, time.monotonic() - start)

    overall = Histogram()
    per_call: dict[str, dict] = {}
    errors = divergence = 0
    for call, lat, status, rec_status in results:
        overall.observe(lat)
        c = per_call.setdefault(
            call, {"sent": 0, "errors": 0, "divergence": 0,
                   "hist": Histogram()},
        )
        c["sent"] += 1
        c["hist"].observe(lat)
        if status >= 400:
            errors += 1
            c["errors"] += 1
        if status != rec_status:
            divergence += 1
            c["divergence"] += 1
    mode = (
        f"closed-loop:{closed_loop}" if closed_loop
        else (f"qps:{qps:g}" if qps else f"speed:{speed:g}")
    )
    return {
        "mode": mode,
        "records": len(records),
        "completed": len(results),
        "transportFailures": len(failures),
        "elapsedSeconds": round(elapsed, 3),
        "qps": round(len(results) / elapsed, 3),
        "p50Ms": round(overall.percentile(0.5) * 1e3, 3),
        "p95Ms": round(overall.percentile(0.95) * 1e3, 3),
        "errorRate": round(errors / max(1, len(results)), 6),
        "divergence": divergence,
        "perCall": {
            call: {
                "sent": c["sent"],
                "share": round(c["sent"] / max(1, len(results)), 4),
                "qps": round(c["sent"] / elapsed, 3),
                "p50Ms": round(c["hist"].percentile(0.5) * 1e3, 3),
                "p95Ms": round(c["hist"].percentile(0.95) * 1e3, 3),
                "errors": c["errors"],
                "divergence": c["divergence"],
            }
            for call, c in sorted(per_call.items())
        },
    }
