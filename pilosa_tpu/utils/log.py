"""Minimal logger (reference: logger.go's Logger interface + the
server's log-path config): one sink, line-oriented, safe from
concurrent handler threads. stderr by default; a configured log-path
appends to a file the operator can rotate externally (reopen-on-HUP is
out of scope — upstream relied on external rotation too).
"""

from __future__ import annotations

import sys
import threading
import time

from pilosa_tpu.utils import sanitize


class Logger:
    def __init__(self, path: str | None = None):
        self._lock = sanitize.make_lock("Logger._lock", loop_safe=True)
        self._file = open(path, "a") if path else None

    def log(self, msg: str) -> None:
        stamp = time.strftime("%Y-%m-%dT%H:%M:%S")
        line = f"{stamp} [pilosa-tpu] {msg}\n"
        with self._lock:
            sink = self._file if self._file is not None else sys.stderr
            sink.write(line)
            sink.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
