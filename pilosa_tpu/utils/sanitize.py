"""Runtime concurrency sanitizer — the dynamic half of the lock rules.

The static analyzer predicts the holds-A-while-acquiring-B graph from
source (``tools/analysis/rules/locks.py``); this module OBSERVES it.
Behind ``PILOSA_TPU_SANITIZE=1``, ``make_lock`` returns an instrumented
wrapper that records, per thread, the stack of sanitized locks held and
derives:

- the observed lock-order graph (every held→acquiring pair, counted);
- hold times per lock (total/max — a lock held for milliseconds on a
  hot path is a latency bug even without a deadlock);
- event-loop-thread findings: any BLOCKING acquire of a lock not
  registered ``loop_safe`` on the thread ``mark_loop_thread()`` marked
  (the deterministic runtime form of the ``loop-purity`` rule);
- cycles in the observed graph (AB/BA deadlocks that merely have not
  fired yet);
- observed edges the static analysis never predicted, when
  ``PILOSA_TPU_SANITIZE_STATIC`` points at the JSON from
  ``python -m tools.analysis --emit-lock-graph`` (inline JSON works
  too) — a mismatch means the call-graph under-approximated and the
  static rules have a blind spot worth closing.

With the env var unset, ``make_lock`` returns the raw lock (or the
``inner`` shim passed in, e.g. a ``saturation.ContendedLock``): the
production fast path pays ZERO overhead — not even an ``if``.

Reports surface three ways: ``report()`` (served at
``/debug/sanitize``), an atexit line to stderr when there are
findings, and the pytest gate (``tests/conftest.py`` fails the session
under ``make sanitize`` if ``findings()`` is non-empty).  See
docs/concurrency.md.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time

__all__ = [
    "enabled",
    "make_lock",
    "mark_loop_thread",
    "unmark_loop_thread",
    "loop_thread_marked",
    "report",
    "findings",
    "reset",
]

_ENV = "PILOSA_TPU_SANITIZE"
_ENV_STATIC = "PILOSA_TPU_SANITIZE_STATIC"

_data_lock = threading.Lock()  # guards every structure below
_locks: dict[str, "SanitizedLock"] = {}
_edges: dict[tuple[str, str], int] = {}
_loop_violations: dict[str, int] = {}
_loop_thread: int | None = None
_tl = threading.local()
_atexit_registered = False


def enabled() -> bool:
    return os.environ.get(_ENV, "") not in ("", "0")


def _stack() -> list:
    st = getattr(_tl, "stack", None)
    if st is None:
        st = _tl.stack = []
    return st


def mark_loop_thread(ident: int | None = None) -> None:
    """Declare the current (or given) thread as THE event-loop thread.
    Safe to call when the sanitizer is off (no-op)."""
    global _loop_thread
    if not enabled():
        return
    _loop_thread = ident if ident is not None else threading.get_ident()


def unmark_loop_thread(ident: int | None = None) -> None:
    """Clear the mark when the loop exits.  The OS REUSES thread
    idents: a mark outliving its loop would flag an unrelated worker
    thread that later receives the same ident.  Only the marked
    thread's own exit clears it, so a second live loop's mark is never
    clobbered by the first one shutting down."""
    global _loop_thread
    if ident is None:
        ident = threading.get_ident()
    if _loop_thread == ident:
        _loop_thread = None


def loop_thread_marked() -> bool:
    return _loop_thread is not None


class SanitizedLock:
    """Lock wrapper recording held-stack edges, hold times, and
    loop-thread acquires.  Exposes ``acquire``/``release`` and the
    context protocol, so ``threading.Condition`` wraps it unmodified
    (Condition's default ``_is_owned`` probes via ``acquire(False)``,
    which records nothing — only SUCCESSFUL acquires enter the held
    stack, and self-edges are never recorded)."""

    __slots__ = (
        "name", "loop_safe", "reentrant", "_inner",
        "acquisitions", "hold_total_s", "hold_max_s",
    )

    def __init__(self, name: str, inner, *, reentrant: bool, loop_safe: bool):
        self.name = name
        self._inner = inner
        self.reentrant = reentrant
        self.loop_safe = loop_safe
        self.acquisitions = 0
        self.hold_total_s = 0.0
        self.hold_max_s = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _stack()
        if blocking:
            # record the HAZARD at attempt time — if this acquire
            # deadlocks, the edge that explains it must already be in
            # the graph
            held = [e for e in st if e[0] is not self]
            if held or (
                _loop_thread is not None
                and not self.loop_safe
                and threading.get_ident() == _loop_thread
            ):
                with _data_lock:
                    for lk, _t0 in held:
                        key = (lk.name, self.name)
                        _edges[key] = _edges.get(key, 0) + 1
                    if (
                        _loop_thread is not None
                        and not self.loop_safe
                        and threading.get_ident() == _loop_thread
                    ):
                        _loop_violations[self.name] = (
                            _loop_violations.get(self.name, 0) + 1
                        )
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self.acquisitions += 1
            st.append((self, time.monotonic()))
        return ok

    def release(self) -> None:
        st = _stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] is self:
                _lk, t0 = st.pop(i)
                held_s = time.monotonic() - t0
                self.hold_total_s += held_s
                if held_s > self.hold_max_s:
                    self.hold_max_s = held_s
                break
        self._inner.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return inner_locked() if inner_locked is not None else False

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


def make_lock(
    name: str,
    *,
    reentrant: bool = False,
    loop_safe: bool = False,
    inner=None,
):
    """THE lock constructor for instrumented subsystems.

    ``name`` uses the static analyzer's lexical identity
    (``ClassName.attr`` — e.g. ``"ResultCache._lock"``) so the observed
    graph lines up with the predicted one.  ``inner`` composes with an
    existing shim (``saturation.ContendedLock``); otherwise a plain
    ``Lock``/``RLock`` is built.  ``loop_safe=True`` asserts the lock is
    bounded and safe to take on the event-loop thread — the claim every
    loop-purity allow pragma makes, now checked at runtime."""
    if inner is None:
        inner = threading.RLock() if reentrant else threading.Lock()
    if not enabled():
        return inner
    lk = SanitizedLock(name, inner, reentrant=reentrant, loop_safe=loop_safe)
    global _atexit_registered
    with _data_lock:
        _locks[name] = lk
        if not _atexit_registered:
            _atexit_registered = True
            atexit.register(_atexit_report)
    return lk


# ------------------------------------------------------------- reporting
def _cycles(edges: dict[tuple[str, str], int]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    out: list[list[str]] = []
    reported: set[frozenset] = set()

    def dfs(start: str, node: str, path: list[str], visiting: set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in reported:
                    reported.add(key)
                    out.append(path + [start])
            elif nxt not in visiting:
                visiting.add(nxt)
                dfs(start, nxt, path + [nxt], visiting)
                visiting.discard(nxt)

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return out


def _load_static() -> dict | None:
    raw = os.environ.get(_ENV_STATIC, "").strip()
    if not raw:
        return None
    try:
        if raw.startswith("{"):
            return json.loads(raw)
        with open(raw, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _names_match(static_name: str, observed: str) -> bool:
    """`*.attr` static nodes (receiver not lexically resolvable) match
    any observed lock with that attribute."""
    if static_name == observed:
        return True
    if static_name.startswith("*.") and observed.endswith(static_name[1:]):
        return True
    return False


def _unexplained(
    observed: dict[tuple[str, str], int], static: dict
) -> list[dict]:
    """Observed edges with no static explanation.  An edge A→B is
    explained when the static graph has a PATH from a node matching A
    to a node matching B — the static closure may know the edge only
    through an intermediate lock the dynamic run never contended on."""
    sedges = [tuple(e[:2]) for e in static.get("edges", [])]
    adj: dict[str, set[str]] = {}
    for a, b in sedges:
        adj.setdefault(a, set()).add(b)
    nodes = set(adj) | {b for _a, bs in adj.items() for b in bs}

    def explained(a: str, b: str) -> bool:
        frontier = [n for n in nodes if _names_match(n, a)]
        seen = set(frontier)
        while frontier:
            cur = frontier.pop()
            if _names_match(cur, b) or any(
                _names_match(t, b) for t in adj.get(cur, ())
            ):
                return True
            for t in adj.get(cur, ()):
                if t not in seen:
                    seen.add(t)
                    frontier.append(t)
        return False

    out = []
    for (a, b), count in sorted(observed.items()):
        if not explained(a, b):
            out.append({"held": a, "acquiring": b, "count": count})
    return out


def report() -> dict:
    """The full sanitizer report — served at ``/debug/sanitize`` and
    consumed by the conftest gate."""
    if not enabled():
        return {"enabled": False}
    with _data_lock:
        locks = {
            name: {
                "acquisitions": lk.acquisitions,
                "loopSafe": lk.loop_safe,
                "holdSecondsTotal": round(lk.hold_total_s, 6),
                "holdSecondsMax": round(lk.hold_max_s, 6),
            }
            for name, lk in sorted(_locks.items())
        }
        observed = dict(_edges)
        loop_v = dict(_loop_violations)
    rep: dict = {
        "enabled": True,
        "loopThreadMarked": _loop_thread is not None,
        "locks": locks,
        "edges": [
            {"held": a, "acquiring": b, "count": c}
            for (a, b), c in sorted(observed.items())
        ],
        "cycles": _cycles(observed),
        "loopThreadViolations": loop_v,
    }
    static = _load_static()
    if static is not None:
        rep["staticComparison"] = {
            "staticEdges": len(static.get("edges", [])),
            "unexplainedEdges": _unexplained(observed, static),
        }
    return rep


def findings(rep: dict | None = None) -> list[str]:
    """Human-readable gate findings: empty list == clean run."""
    rep = rep if rep is not None else report()
    if not rep.get("enabled"):
        return []
    out = []
    for cyc in rep.get("cycles", []):
        out.append("lock-order cycle observed: " + " -> ".join(cyc))
    for name, count in sorted(rep.get("loopThreadViolations", {}).items()):
        out.append(
            f"non-loop_safe lock {name} blocking-acquired on the "
            f"event-loop thread ({count}x)"
        )
    for e in rep.get("staticComparison", {}).get("unexplainedEdges", []):
        out.append(
            f"observed edge {e['held']} -> {e['acquiring']} "
            f"({e['count']}x) absent from the static lock graph"
        )
    return out


def reset() -> None:
    """Drop all recorded state (test isolation)."""
    global _loop_thread
    with _data_lock:
        _locks.clear()
        _edges.clear()
        _loop_violations.clear()
    _loop_thread = None


def _atexit_report() -> None:
    found = findings()
    if found:
        sys.stderr.write(
            "[pilosa-tpu sanitize] %d finding(s):\n" % len(found)
        )
        for line in found:
            sys.stderr.write(f"[pilosa-tpu sanitize]   {line}\n")
