"""X1 cross-cutting utilities: stats, tracing, config, logging."""

from pilosa_tpu.utils.stats import Histogram, NopStats, StatsClient
from pilosa_tpu.utils.tracing import GLOBAL_TRACER, Tracer

__all__ = ["StatsClient", "NopStats", "Histogram", "Tracer", "GLOBAL_TRACER"]
