"""X1 cross-cutting utilities: stats, tracing, config, logging."""

from pilosa_tpu.utils.stats import NopStats, StatsClient
from pilosa_tpu.utils.tracing import GLOBAL_TRACER, Tracer

__all__ = ["StatsClient", "NopStats", "Tracer", "GLOBAL_TRACER"]
