"""Always-on continuous sampling profiler: flame graphs one curl away.

``utils/profiling.py``'s ``sample_profile`` answers "what is the process
doing for the NEXT five seconds" — useless for the p99 spike that
already happened.  This module keeps a background sampler running for
the life of the server (config ``profiler-enabled``), aggregating
``sys._current_frames()`` samples into a bounded folded-stack table
held as a ring of rotating time segments, so ``GET /debug/profile``
serves a flame graph of the last minute (or any retained historical
segment) instantly — nothing to arm in advance, same philosophy as the
flight recorder's tail-based retention.

Design constraints, in order:

- **Bounded overhead.**  The c1 p50 gate is ≤1.03x with the sampler on
  (make bench-profile).  Two levers: low default rate (20 Hz — a 60 s
  segment still lands ~1200 samples), and a folded-stack CACHE keyed on
  the top frame object — a parked thread's stack is the *same frame
  objects* every sample, so the steady-state cost per idle thread is
  one dict lookup, not a frame walk.
- **Bounded memory.**  Each segment caps distinct stacks at
  ``max_stacks`` (overflow folds into ``<subsystem>;(other)``) and the
  ring caps retained segments; memory is O(segments × max_stacks).
- **Attribution by subsystem.**  Stacks are rooted at the sampled
  thread's NAME with trailing pool indices stripped ("http-worker_3" →
  "http-worker"), so the flame graph reads per subsystem — which is why
  every background thread in the package is named at spawn.

Formats: folded text (``stack count`` lines, flamegraph.pl /
inferno-ready) and speedscope JSON (https://speedscope.app), plus a
segment index for the historical ring.  The flight recorder stamps each
retained query with the segment ids overlapping its wall-clock window,
linking a slow query straight to the flame graph that contains it.
"""

from __future__ import annotations

import re
import sys
import threading
import time
from typing import Callable

from pilosa_tpu.utils.profiling import _folded

# "http-worker_3" / "compactor-1" → "http-worker" / "compactor": pool
# members fold into one subsystem root
_POOL_SUFFIX = re.compile(r"[-_]\d+$")


def subsystem_of(thread_name: str) -> str:
    return _POOL_SUFFIX.sub("", thread_name) or thread_name


class _Segment:
    __slots__ = ("id", "start", "end", "samples", "counts")

    def __init__(self, seg_id: int, start: float):
        self.id = seg_id
        self.start = start
        self.end: float | None = None  # None while current
        self.samples = 0
        self.counts: dict[str, int] = {}

    def info(self) -> dict:
        return {
            "id": self.id,
            "startMonotonicS": self.start,
            "endMonotonicS": self.end,
            "samples": self.samples,
            "stacks": len(self.counts),
        }


class SamplingProfiler:
    """The background sampler + segment ring.  One instance per server
    process (Server.open constructs it from config and hands it to the
    listener for ``/debug/profile``)."""

    def __init__(
        self,
        hz: float = 20.0,
        segment_s: float = 60.0,
        segments: int = 16,
        max_stacks: int = 4096,
        stats=None,
        enabled: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.hz = max(1.0, min(float(hz), 250.0))
        self.segment_s = max(1.0, float(segment_s))
        self.max_stacks = max(16, int(max_stacks))
        self.enabled = bool(enabled)
        self.stats = stats
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: list[_Segment] = []
        self._ring_cap = max(1, int(segments))
        self._seq = 0
        self._current = _Segment(self._seq, self._clock())
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # folded-stack cache keyed on the top AND caller frame identity
        # (tid, id(frame), f_lasti, id(f_back), back f_lasti).  A parked
        # thread re-presents the identical frame objects every sample;
        # the hit turns its per-sample cost into a dict lookup.  The
        # caller frame is in the key because frame objects are
        # freelisted: a dead leaf frame's address can be recycled by a
        # NEW frame parked at the same f_lasti (every parked thread
        # leads in threading.wait), and the leaf identity alone would
        # then misattribute the whole stack until the cache cleared.
        self._folded_cache: dict[tuple, str] = {}
        self._names: dict[int, str] = {}

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        # restartable: stop() left _stop set; a reused flag would make
        # the new sampler exit on its first wait
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="profiler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        interval = 1.0 / self.hz
        stop = self._stop  # the Event THIS run was started with
        while not stop.wait(interval):
            self.sample_once()

    # ------------------------------------------------------------ sampling
    def _thread_name(self, tid: int) -> str:
        name = self._names.get(tid)
        if name is None:
            self._names = {
                t.ident: t.name for t in threading.enumerate()
                if t.ident is not None
            }
            name = self._names.get(tid, f"thread-{tid}")
        return name

    def sample_once(self) -> None:
        """One pass over every live thread's stack (called by the
        sampler thread; public so tests drive it with a fake clock)."""
        me = threading.get_ident()
        now = self._clock()
        frames = sys._current_frames()
        cache = self._folded_cache
        if len(cache) > 8192:
            cache.clear()  # bound against frame-id churn
        with self._lock:
            cur = self._current
            for tid, frame in frames.items():
                if tid == me:
                    continue
                back = frame.f_back
                key = (
                    tid, id(frame), frame.f_lasti,
                    id(back), back.f_lasti if back is not None else -1,
                )
                stack = cache.get(key)
                if stack is None:
                    name = self._thread_name(tid)
                    stack = subsystem_of(name) + ";" + _folded(frame)
                    cache[key] = stack
                counts = cur.counts
                if stack in counts:
                    counts[stack] += 1
                elif len(counts) < self.max_stacks:
                    counts[stack] = 1
                else:
                    other = stack.split(";", 1)[0] + ";(other)"
                    counts[other] = counts.get(other, 0) + 1
            cur.samples += 1
            if now - cur.start >= self.segment_s:
                self._rotate_locked(now)
        if self.stats is not None:
            self.stats.count("profiler_samples_total")

    def _rotate_locked(self, now: float) -> None:
        self._current.end = now
        self._ring.append(self._current)
        if len(self._ring) > self._ring_cap:
            del self._ring[0]
        self._seq += 1
        self._current = _Segment(self._seq, now)

    # ------------------------------------------------------------- surface
    @property
    def current_segment_id(self) -> int:
        return self._current.id

    def segments_info(self) -> list[dict]:
        with self._lock:
            out = [s.info() for s in self._ring]
            out.append(self._current.info())
        return out

    def segments_overlapping(self, t0: float, t1: float) -> list[int]:
        """Segment ids whose [start, end) window intersects [t0, t1] —
        the flight-recorder linkage for a retained query's wall-clock
        span."""
        out = []
        with self._lock:
            for s in [*self._ring, self._current]:
                end = s.end if s.end is not None else float("inf")
                if s.start <= t1 and end >= t0:
                    out.append(s.id)
        return out

    def _window(
        self, seconds: float | None, segment: int | None
    ) -> tuple[dict[str, int], int, float, str]:
        """(merged counts, samples, span seconds, label) for a query:
        one historical segment by id, the segments covering the last
        ``seconds``, or (default) the whole retained ring."""
        now = self._clock()
        with self._lock:
            segs = [*self._ring, self._current]
            if segment is not None:
                segs = [s for s in segs if s.id == segment]
                if not segs:
                    raise KeyError(f"segment {segment} not retained")
                label = f"segment {segment}"
            elif seconds is not None:
                cutoff = now - seconds
                segs = [
                    s for s in segs
                    if (s.end if s.end is not None else now) >= cutoff
                ]
                label = f"last {seconds:g}s"
            else:
                label = "all retained segments"
            merged: dict[str, int] = {}
            samples = 0
            span = 0.0
            for s in segs:
                samples += s.samples
                span += (s.end if s.end is not None else now) - s.start
                for stack, n in s.counts.items():
                    merged[stack] = merged.get(stack, 0) + n
        return merged, samples, span, label

    def folded(
        self, seconds: float | None = None, segment: int | None = None
    ) -> str:
        """Folded-stack text (``a;b;c count``), heaviest first, with a
        header comment naming the window — flamegraph.pl input."""
        merged, samples, span, label = self._window(seconds, segment)
        lines = [
            f"# {samples} samples over {span:.1f}s at ~{self.hz:g} Hz"
            f" ({label})"
        ]
        for stack, n in sorted(merged.items(), key=lambda kv: -kv[1]):
            lines.append(f"{stack} {n}")
        return "\n".join(lines) + "\n"

    def speedscope(
        self, seconds: float | None = None, segment: int | None = None
    ) -> dict:
        """speedscope.app file: one sampled profile whose weights are
        sample counts scaled to seconds (count / hz)."""
        merged, samples, span, label = self._window(seconds, segment)
        frame_index: dict[str, int] = {}
        frames: list[dict] = []
        sample_stacks: list[list[int]] = []
        weights: list[float] = []
        dt = 1.0 / self.hz
        for stack, n in sorted(merged.items(), key=lambda kv: -kv[1]):
            idxs = []
            for part in stack.split(";"):
                i = frame_index.get(part)
                if i is None:
                    i = frame_index[part] = len(frames)
                    frames.append({"name": part})
                idxs.append(i)
            sample_stacks.append(idxs)
            weights.append(n * dt)
        total = sum(weights)
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "exporter": "pilosa-tpu",
            "name": f"pilosa-tpu {label}",
            "shared": {"frames": frames},
            "profiles": [
                {
                    "type": "sampled",
                    "name": label,
                    "unit": "seconds",
                    "startValue": 0,
                    "endValue": total,
                    "samples": sample_stacks,
                    "weights": weights,
                }
            ],
            "activeProfileIndex": 0,
        }

    def snapshot(self) -> dict:
        """Meta view for /debug/profile?format=segments and the doctor
        bundle: config + the segment index."""
        t = self._thread
        return {
            "enabled": self.enabled,
            # liveness, not thread-object presence: a sampler that died
            # must not read as running while the ring silently freezes
            "running": t is not None and t.is_alive(),
            "hz": self.hz,
            "segmentSeconds": self.segment_s,
            "ringCapacity": self._ring_cap,
            "currentSegment": self.current_segment_id,
            "segments": self.segments_info(),
        }
