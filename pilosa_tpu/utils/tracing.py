"""Tracing: spans around executor calls, fragment ops, HTTP handlers —
with REAL trace identity and cross-node context propagation.

Reference: tracing/tracing.go (global Tracer, StartSpanFromContext) +
tracing/opentracing adapter (Jaeger span propagation across the per-shard
HTTP fan-out). OpenTracing/Jaeger isn't available here, so the Tracer
records spans in-process (ring buffer) and can dump them for inspection;
the API matches so an OTLP adapter can slot in later. What IS wire-real:

- every span carries a 128-bit ``trace_id`` and 64-bit ``span_id``
  (hex strings, Jaeger-sized);
- ``(trace_id, parent_span_id)`` travel node→node as HTTP headers
  (``X-Pilosa-Trace-Id`` / ``X-Pilosa-Parent-Span-Id``, injected by
  parallel/client.py and extracted by server/http.py), so one user query
  yields ONE coherent trace across coordinator and remote nodes;
- ``chrome_trace_stitched`` merges per-node span sets into one Chrome
  trace-event JSON (one pid per node) for Perfetto/chrome://tracing —
  the export story, with the coordinator fetching remote spans via
  ``GET /internal/trace``.

The module also hosts the per-query profile collector (``profile_query``
/ ``current_profile``): a thread-local sink the executor and cluster
fan-out write per-call / per-shard-group timing+bytes records into, so
``?profile=true`` can return a breakdown without threading a collector
through every router signature.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager

MAX_SPANS = 4096

# cross-node propagation headers (reference: the opentracing adapter's
# Inject/Extract over Jaeger's uber-trace-id; spelled out here so curl
# can join a trace too)
TRACE_HEADER = "X-Pilosa-Trace-Id"
PARENT_HEADER = "X-Pilosa-Parent-Span-Id"


def new_trace_id() -> str:
    """128-bit trace id, 32 hex chars (Jaeger-sized)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit span id, 16 hex chars."""
    return os.urandom(8).hex()


# one wall↔monotonic anchor so exported timestamps share a single
# monotonic timeline (mixing time.time starts with perf_counter
# durations lets child slices cross parent boundaries in trace viewers)
_PERF_EPOCH = time.time() - time.perf_counter()  # pilosa: allow(wall-clock)


class Span:
    __slots__ = (
        "name",
        "start",
        "start_perf",
        "duration",
        "tags",
        "parent",
        "tid",
        "trace_id",
        "span_id",
        "parent_id",
    )

    def __init__(
        self,
        name: str,
        parent: str | None = None,
        trace_id: str | None = None,
        parent_id: str | None = None,
    ):
        self.name = name
        self.parent = parent  # parent span NAME (human-readable)
        self.trace_id = trace_id or new_trace_id()
        self.span_id = new_span_id()
        self.parent_id = parent_id  # parent span ID (joinable)
        self.start = time.time()
        self.start_perf = time.perf_counter()
        self.duration = 0.0
        self.tags: dict = {}
        self.tid = threading.get_ident()

    def set_tag(self, k, v):
        self.tags[k] = v

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "parent": self.parent,
            "traceID": self.trace_id,
            "spanID": self.span_id,
            "parentSpanID": self.parent_id,
            "start": self.start,
            # wall-anchored monotonic start: chrome export needs ts and
            # dur on ONE clock, and remote spans arrive as these dicts
            "ts": self.start_perf + _PERF_EPOCH,
            "durationSeconds": self.duration,
            "tags": self.tags,
            "tid": self.tid,
        }


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans: deque[Span] = deque(maxlen=MAX_SPANS)
        self._local = threading.local()

    @contextmanager
    def span(self, name: str, **tags):
        parent = getattr(self._local, "current", None)
        if parent is not None:
            s = Span(
                name,
                parent=parent.name,
                trace_id=parent.trace_id,
                parent_id=parent.span_id,
            )
        else:
            # no local parent: join a propagated (remote) context if one
            # was activated for this request, else start a fresh trace
            remote = getattr(self._local, "remote", None)
            if remote is not None:
                s = Span(name, trace_id=remote[0], parent_id=remote[1])
            else:
                s = Span(name)
        s.tags.update(tags)
        self._local.current = s
        try:
            yield s
        finally:
            # same sample as the exported ts — ts and dur must share one
            # clock origin or child slices cross parent edges in viewers
            s.duration = time.perf_counter() - s.start_perf
            self._local.current = parent
            with self._lock:
                self._spans.append(s)

    @contextmanager
    def activate(self, trace_id: str | None, parent_span_id: str | None):
        """Join a PROPAGATED trace context for the duration of a request:
        spans opened on this thread (with no local parent) adopt
        ``trace_id`` and parent onto ``parent_span_id`` — the server-side
        Extract half of cross-node propagation. A falsy trace_id is a
        no-op so call sites don't need to branch on header presence."""
        if not trace_id:
            yield
            return
        prev = getattr(self._local, "remote", None)
        self._local.remote = (trace_id, parent_span_id)
        try:
            yield
        finally:
            self._local.remote = prev

    @contextmanager
    def detached(self, trace_id: str | None, parent_span_id: str | None):
        """Run the body OUTSIDE this thread's current span stack,
        optionally joining a propagated context instead.  The wave
        scheduler (executor/scheduler.py) executes queued queries on
        the leader's thread: each query's spans must join the
        SUBMITTER's trace (captured at enqueue), not nest under the
        leader's own request span — otherwise every batched query's
        trace would collapse into whichever request happened to lead
        the wave."""
        prev_cur = getattr(self._local, "current", None)
        prev_rem = getattr(self._local, "remote", None)
        self._local.current = None
        self._local.remote = (trace_id, parent_span_id) if trace_id else None
        try:
            yield
        finally:
            self._local.current = prev_cur
            self._local.remote = prev_rem

    def current_context(self) -> tuple[str, str] | None:
        """(trace_id, span_id) to INJECT into an outbound request — the
        active span's identity, or the activated remote context when no
        span is open on this thread. None outside any trace."""
        cur = getattr(self._local, "current", None)
        if cur is not None:
            return (cur.trace_id, cur.span_id)
        remote = getattr(self._local, "remote", None)
        if remote is not None and remote[0]:
            return (remote[0], remote[1] or "")
        return None

    def current_trace_id(self) -> str | None:
        ctx = self.current_context()
        return ctx[0] if ctx else None

    def recent(self, n: int = 100) -> list[dict]:
        with self._lock:
            return [s.to_json() for s in list(self._spans)[-n:]]

    def depth(self) -> int:
        """Buffered span count (the /debug/resources tracer-ring row —
        counting must not pay for serializing 4k spans)."""
        with self._lock:
            return len(self._spans)

    def spans_for_trace(self, trace_id: str) -> list[dict]:
        """Every buffered span belonging to one trace (served to peers by
        GET /internal/trace for cross-node stitching)."""
        with self._lock:
            return [s.to_json() for s in self._spans if s.trace_id == trace_id]

    def chrome_trace(self, n: int = 1000) -> dict:
        """Spans as Chrome trace-event JSON — loadable in
        chrome://tracing / Perfetto (the trace-EXPORT story; the
        reference exports spans to Jaeger, unavailable here)."""
        with self._lock:
            spans = [s.to_json() for s in list(self._spans)[-n:]]
        return {
            "traceEvents": _chrome_events(spans, pid=1),
            "displayTimeUnit": "ms",
        }


def _chrome_events(spans: list[dict], pid: int) -> list[dict]:
    """Span dicts (Span.to_json shape — local or fetched from a peer) →
    Chrome trace-event "X" slices on one pid."""
    events = []
    for s in spans:
        args = dict(s.get("tags") or {})
        if s.get("parent"):
            args["parent"] = s["parent"]
        for key in ("traceID", "spanID", "parentSpanID"):
            if s.get(key):
                args[key] = s[key]
        events.append(
            {
                "name": s["name"],
                "ph": "X",
                # one monotonic timeline anchored to wall time — ts and
                # dur must share a clock or nesting breaks
                "ts": s["ts"] * 1e6,
                "dur": s["durationSeconds"] * 1e6,
                "pid": pid,
                "tid": s.get("tid", 1),
                "args": args,
            }
        )
    return events


def chrome_trace_stitched(spans_by_node: dict[str, list[dict]]) -> dict:
    """One coherent Chrome trace from per-node span sets: each node gets
    its own pid (named via process_name metadata), every event keeps its
    traceID/spanID/parentSpanID args, so a distributed query renders as
    the coordinating HTTP span with each remote node's spans time-nested
    inside it on their own process track."""
    events: list[dict] = []
    for pid, node in enumerate(sorted(spans_by_node), start=1):
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"node {node}"},
            }
        )
        events.extend(_chrome_events(spans_by_node[node], pid=pid))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


GLOBAL_TRACER = Tracer()


# --------------------------------------------------------- query profiles
class QueryProfile:
    """Per-query timing/bytes breakdown (the reference's query-profile
    analogue). Filled by the executor (per-PQL-call dispatch + readback)
    and the cluster fan-out (per-node shard groups, RPC latency + wire
    bytes); surfaced by ``?profile=true`` and mined by the long-query
    log to name the slow shard group. Single-threaded by construction:
    the HTTP handler thread drives the whole query synchronously."""

    __slots__ = (
        "trace_id",
        "total_seconds",
        "calls",
        "fanout",
        "wave",
        "mesh",
        "residency",
        "admission_wait",
        "deadline",
        "retries",
        "failovers",
        "_last_rpc_bytes",
    )

    def __init__(self):
        self.trace_id: str | None = None
        self.total_seconds = 0.0
        self.calls: list[dict] = []  # local executor per-call entries
        self.fanout: list[dict] = []  # per-node shard-group entries
        # seconds this request waited in the event front end's admission
        # queue before a worker picked it up (None on the threaded
        # listener, which has no admission lane) — the flight recorder's
        # "was it the queue or the query" attribution
        self.admission_wait: float | None = None
        # per-query deadline accounting at settle: {"budgetS",
        # "remainingS"} — how much of the promised budget the query
        # spent (docs/fault-tolerance.md)
        self.deadline: dict | None = None
        # retry/failover attribution (docs/fault-tolerance.md): the
        # resilient RPC chain notes each retry sleep it takes on this
        # query's behalf, and the fan-out notes each leg it re-planned
        # onto a surviving replica — tail latency from a flaky peer is
        # visible in the evidence, not just in global counters
        self.retries: list[dict] = []
        self.failovers: list[dict] = []
        # set by the wave scheduler when this query rode a shared wave:
        # {"queries": occupancy, "flushReason": ...} — the ?profile=true
        # surface for cross-query coalescing
        self.wave: dict | None = None
        # set by the executor when a call routed to the explicit-SPMD
        # mesh path: device count + mesh geometry (the ?profile=true
        # surface for multi-chip execution; per-call entries carry the
        # route tag already)
        self.mesh: dict | None = None
        # set by the executor when the query touched tiered compressed
        # residency (docs/device-residency.md): container tiers,
        # promotion/demotion counters — the ?profile=true surface for
        # the hot/cold row tier
        self.residency: dict | None = None
        self._last_rpc_bytes = 0

    def add_call(
        self,
        call: str,
        seconds: float,
        shards: list[int] | None,
        route: str | None = None,
    ) -> None:
        # shards is stored by REFERENCE, not copied: the collector runs
        # on every query (the long-query log mines it), so a thousands-
        # of-shards index must not pay a per-call list copy; callers
        # pass lists they do not mutate afterwards
        entry: dict = {"call": call, "seconds": seconds}
        if route is not None:
            # which engine the cost router picked (host | device) — the
            # ?profile=true surface for the routing decision
            entry["route"] = route
        if shards is not None:
            entry["shards"] = shards
        self.calls.append(entry)

    def add_fanout(
        self,
        call: str,
        node: str,
        shards: list[int] | None,
        seconds: float,
        bytes_: int,
    ) -> None:
        self.fanout.append(
            {
                "call": call,
                "node": node,
                "shards": shards,  # by reference — see add_call
                "seconds": seconds,
                "bytes": bytes_,
            }
        )

    def note_retry(self, method: str, node: str, attempt: int) -> None:
        """The resilient client reports each retry attempt it makes for
        an RPC issued under this query (docs/fault-tolerance.md)."""
        self.retries.append({"method": method, "node": node, "attempt": attempt})

    def note_failover(self, node: str, to_node: str, shards: list[int] | None) -> None:
        """The cluster fan-out reports each leg it re-planned from a
        failed peer onto a surviving replica."""
        self.failovers.append(
            {"node": node, "toNode": to_node, "shards": shards}
        )

    def note_rpc_bytes(self, n: int) -> None:
        """The internal client reports each response's size here; the
        fan-out reads it back to attribute wire bytes to the shard-group
        entry it is about to record (same thread, no nesting between the
        RPC return and the read)."""
        self._last_rpc_bytes = n

    def take_rpc_bytes(self) -> int:
        n, self._last_rpc_bytes = self._last_rpc_bytes, 0
        return n

    def slowest(self) -> dict | None:
        """The slowest shard-group (preferred — it names a node) or
        per-call entry, for the long-query log."""
        pool = self.fanout or self.calls
        if not pool:
            return None
        return max(pool, key=lambda e: e["seconds"])

    def to_json(self) -> dict:
        out: dict = {
            "totalSeconds": self.total_seconds,
            "calls": self.calls,
            "fanout": self.fanout,
        }
        if self.wave is not None:
            out["wave"] = self.wave
        if self.mesh is not None:
            out["mesh"] = self.mesh
        if self.residency is not None:
            out["residency"] = self.residency
        if self.admission_wait is not None:
            out["admissionWaitSeconds"] = self.admission_wait
        if self.deadline is not None:
            out["deadline"] = self.deadline
        if self.retries:
            out["retries"] = self.retries
        if self.failovers:
            out["failovers"] = self.failovers
        if self.trace_id:
            out["traceID"] = self.trace_id
        return out


_PROFILE = threading.local()


@contextmanager
def profile_query():
    """Install a QueryProfile as this thread's active collector."""
    prof = QueryProfile()
    prev = getattr(_PROFILE, "current", None)
    _PROFILE.current = prof
    try:
        yield prof
    finally:
        _PROFILE.current = prev


def current_profile() -> QueryProfile | None:
    return getattr(_PROFILE, "current", None)


@contextmanager
def use_profile(prof: QueryProfile | None):
    """Install a SPECIFIC profile (possibly None) as this thread's
    collector — the wave scheduler dispatches queued queries on the
    leader's thread, and each query's executor calls must land in the
    profile its own submitter installed, not the leader's."""
    prev = getattr(_PROFILE, "current", None)
    _PROFILE.current = prof
    try:
        yield prof
    finally:
        _PROFILE.current = prev
