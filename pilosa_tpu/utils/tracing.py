"""Tracing: spans around executor calls, fragment ops, HTTP handlers.

Reference: tracing/tracing.go (global Tracer, StartSpanFromContext) +
tracing/opentracing adapter. OpenTracing/Jaeger isn't available here, so
the Tracer records spans in-process (ring buffer) and can dump them for
inspection; the API matches so an OTLP adapter can slot in later.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager

MAX_SPANS = 4096


# one wall↔monotonic anchor so exported timestamps share a single
# monotonic timeline (mixing time.time starts with perf_counter
# durations lets child slices cross parent boundaries in trace viewers)
_PERF_EPOCH = time.time() - time.perf_counter()


class Span:
    __slots__ = ("name", "start", "start_perf", "duration", "tags", "parent", "tid")

    def __init__(self, name: str, parent: str | None = None):
        self.name = name
        self.parent = parent
        self.start = time.time()
        self.start_perf = time.perf_counter()
        self.duration = 0.0
        self.tags: dict = {}
        self.tid = threading.get_ident()

    def set_tag(self, k, v):
        self.tags[k] = v

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "parent": self.parent,
            "start": self.start,
            "durationSeconds": self.duration,
            "tags": self.tags,
        }


class Tracer:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans: deque[Span] = deque(maxlen=MAX_SPANS)
        self._local = threading.local()

    @contextmanager
    def span(self, name: str, **tags):
        parent = getattr(self._local, "current", None)
        s = Span(name, parent=parent.name if parent else None)
        s.tags.update(tags)
        self._local.current = s
        try:
            yield s
        finally:
            # same sample as the exported ts — ts and dur must share one
            # clock origin or child slices cross parent edges in viewers
            s.duration = time.perf_counter() - s.start_perf
            self._local.current = parent
            with self._lock:
                self._spans.append(s)

    def recent(self, n: int = 100) -> list[dict]:
        with self._lock:
            return [s.to_json() for s in list(self._spans)[-n:]]

    def chrome_trace(self, n: int = 1000) -> dict:
        """Spans as Chrome trace-event JSON — loadable in
        chrome://tracing / Perfetto (the trace-EXPORT story; the
        reference exports spans to Jaeger, unavailable here)."""
        with self._lock:
            spans = list(self._spans)[-n:]
        return {
            "traceEvents": [
                {
                    "name": s.name,
                    "ph": "X",
                    # one monotonic timeline anchored to wall time —
                    # ts and dur must share a clock or nesting breaks
                    "ts": (s.start_perf + _PERF_EPOCH) * 1e6,
                    "dur": s.duration * 1e6,
                    "pid": 1,
                    "tid": s.tid,
                    "args": {**s.tags, **({"parent": s.parent} if s.parent else {})},
                }
                for s in spans
            ],
            "displayTimeUnit": "ms",
        }


GLOBAL_TRACER = Tracer()
