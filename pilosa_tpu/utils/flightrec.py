"""Flight recorder: always-on, tail-based retention of slow/errored
query evidence.

The serving path has five decision-making subsystems — the host/device/
mesh cost router, the wave scheduler, tiered device residency, event-
loop admission control, and per-peer circuit breakers — whose choices
were invisible once a request completed: a p99 outlier could only be
diagnosed if ``?profile=true`` happened to be set BEFORE it ran.  The
profile collector already runs on every query (a handful of dict
appends, PR 1's long-query-log design), so the evidence exists at
settle time; what was missing is somewhere for it to go.

This module keeps bounded ring buffers of FULL query evidence — the
profile (per-call route + timing, fan-out legs, wave occupancy,
residency tiers, admission wait, retries/failovers, deadline spend) and
the trace's buffered spans — for every query that either ERRORED or
settled slower than a per-call-type rolling p95 threshold.  The
retention decision is made at settle time (tail-based sampling: by the
time we know the query was slow, the evidence is already collected), so
nothing about the request had to be special.  Upstream Pilosa's
long-query log (PAPER.md) is the ancestor; the rolling per-call-type
threshold replaces its one static ``long-query-time`` knob because a
healthy GroupBy and a healthy Count live an order of magnitude apart.

Surfaces: ``GET /debug/flightrec`` (summaries + thresholds),
``?trace_id=`` (one entry, full profile + spans),
``?trace_id=&format=perfetto`` (the retained spans as Chrome
trace-event JSON — loadable in Perfetto even after the tracer's own
ring buffer has rotated the spans out), and a structured slow-query
log line carrying the trace id emitted at retention time.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable

import bisect

from pilosa_tpu.utils import sanitize
from pilosa_tpu.utils.stats import DEFAULT_BUCKETS, Histogram

# observations per rolling window: the p95 threshold is computed over
# the current + previous windows, so it tracks roughly the last
# 1x-2x WINDOW queries per call type instead of all history — a
# workload shift re-baselines within one window
_WINDOW = 2048
# samples before the p95 threshold is trusted; until then only errors
# retain (a 3-sample "p95" would retain every third query at startup)
_MIN_SAMPLES = 30


class _RollingP95:
    """Per-call-type rolling latency quantile: two log-bucketed windows
    (current + previous) merged for the percentile, rotated when the
    current window fills.  Same bucket boundaries as every serving
    histogram, so the threshold and the dashboards agree."""

    __slots__ = ("cur", "prev", "_rotate_lock")

    def __init__(self):
        self.cur = Histogram()
        self.prev: Histogram | None = None
        self._rotate_lock = sanitize.make_lock("_RollingP95._rotate_lock", loop_safe=True)

    def observe(self, seconds: float) -> None:
        self.cur.observe(seconds)
        if self.cur.count >= _WINDOW:
            # rotation must be check-and-swap atomic: two settles racing
            # the boundary would otherwise both rotate, installing an
            # EMPTY histogram as prev — samples() drops under the
            # minimum and slow-query retention silently suspends
            with self._rotate_lock:
                if self.cur.count >= _WINDOW:
                    self.prev, self.cur = self.cur, Histogram()

    def samples(self) -> int:
        return self.cur.count + (self.prev.count if self.prev else 0)

    def percentile(self, q: float) -> float:
        if self.prev is None or self.prev.count == 0:
            return self.cur.percentile(q)
        merged = Histogram()
        with self.cur._lock, self.prev._lock:
            merged.counts = [
                a + b for a, b in zip(self.cur.counts, self.prev.counts)
            ]
            merged.count = self.cur.count + self.prev.count
            merged.sum = self.cur.sum + self.prev.sum
        return merged.percentile(q)


class FlightRecorder:
    """One recorder per serving front end, shared across request
    threads.  ``settle`` is the single entry: the handler calls it for
    EVERY public query (success or error) with a zero-cost evidence
    thunk; the thunk is only invoked when the query is retained, so the
    steady-state cost of the recorder is one histogram observe plus a
    threshold comparison."""

    def __init__(
        self,
        capacity: int = 256,
        min_latency_s: float = 0.025,
        stats=None,
        log: "Callable[[str], None] | None" = None,
        enabled: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.capacity = max(1, int(capacity))
        self.min_latency_s = float(min_latency_s)
        self.enabled = bool(enabled)
        self.stats = stats
        self.log = log
        self._clock = clock
        self._lock = sanitize.make_lock("FlightRecorder._lock", loop_safe=True)
        self._entries: deque[dict] = deque(maxlen=self.capacity)
        self._quantiles: dict[str, _RollingP95] = {}
        self._seq = 0
        self.retained = {"slow": 0, "error": 0}

    # ------------------------------------------------------------- intake
    def threshold(self, call_type: str) -> float | None:
        """The current retention threshold for one call type — the
        rolling p95, CEILINGED to the next histogram bucket boundary
        and floored at ``min_latency_s`` — or None while the window is
        still too thin to trust (only errors retain then).  The bucket
        ceiling matters: the interpolated p95 of a uniform latency
        profile lands just below the common value, and without the
        ceiling a perfectly healthy call type would retain nearly
        every one of its own queries.  Retention is strictly-greater
        (``settle``), so landing ON the boundary never retains."""
        with self._lock:
            q = self._quantiles.get(call_type)
        if q is None or q.samples() < _MIN_SAMPLES:
            return None
        p95 = q.percentile(0.95)
        i = bisect.bisect_left(DEFAULT_BUCKETS, p95)
        ceiling = (
            DEFAULT_BUCKETS[i] if i < len(DEFAULT_BUCKETS) else p95
        )
        return max(ceiling, self.min_latency_s)

    def settle(
        self,
        call_type: str,
        seconds: float,
        entry_fn: "Callable[[], dict]",
        error: "BaseException | None" = None,
    ) -> bool:
        """The tail-based retention decision, made once per query at
        settle time.  ``entry_fn`` builds the full evidence dict (the
        profile JSON, the trace's spans) and is invoked ONLY when the
        query is retained.  Returns whether the query was retained."""
        if not self.enabled:
            return False
        threshold = None
        if error is None:
            threshold = self.threshold(call_type)
            with self._lock:
                q = self._quantiles.get(call_type)
                if q is None:
                    q = self._quantiles[call_type] = _RollingP95()
            # errored latencies stay out of the window: a run of fast
            # failures would drag the p95 down and retain healthy traffic
            q.observe(seconds)
        retain = error is not None or (
            threshold is not None and seconds > threshold
        )
        if not retain:
            return False
        reason = "error" if error is not None else "slow"
        entry = entry_fn() or {}
        entry["reason"] = reason
        entry["callType"] = call_type
        entry["seconds"] = seconds
        if threshold is not None:
            entry["thresholdSeconds"] = threshold
        if error is not None:
            entry["error"] = f"{type(error).__name__}: {error}"
        entry["monotonicS"] = self._clock()
        # wall timestamp, never used in arithmetic — operators correlate
        # entries with external logs by it
        entry["recordedAt"] = time.time()
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._entries.append(entry)
            self.retained[reason] = self.retained.get(reason, 0) + 1
        if self.stats is not None:
            self.stats.count("flightrec_retained_total", tags={"reason": reason})
        if self.log is not None:
            # the structured slow-query log line: one JSON object so log
            # pipelines can index on traceId without regexes
            self.log(
                "flightrec "
                + json.dumps(
                    {
                        "event": "slow_query" if reason == "slow" else "query_error",
                        "traceId": entry.get("traceId"),
                        "index": entry.get("index"),
                        "call": call_type,
                        "seconds": round(seconds, 6),
                        "thresholdSeconds": (
                            round(threshold, 6) if threshold is not None else None
                        ),
                        "reason": reason,
                        "query": (entry.get("query") or "")[:200],
                        "error": entry.get("error"),
                        # workload linkage (docs/workload.md): this
                        # exact query's fingerprint + current heavy-
                        # hitter rank — "how often does this run" is
                        # one /debug/workload lookup away
                        "fingerprint": entry.get("fingerprint"),
                        "workloadRank": entry.get("workloadRank"),
                        # result-cache verdict (docs/result-cache.md):
                        # "why wasn't this slow query a cache hit"
                        "cache": (entry.get("resultCache") or {}).get(
                            "outcome"
                        ),
                    }
                )
            )
        return True

    # ------------------------------------------------------------ surface
    def entries(self) -> list[dict]:
        """Retained entries, oldest first (full evidence)."""
        with self._lock:
            return list(self._entries)

    def entry(self, trace_id: str) -> dict | None:
        with self._lock:
            for e in reversed(self._entries):
                if e.get("traceId") == trace_id:
                    return e
        return None

    def snapshot(self) -> dict:
        """The ``GET /debug/flightrec`` listing: entry SUMMARIES (the
        full profile/spans stay behind ``?trace_id=`` so the listing
        stays small), live thresholds, and retention counters."""
        with self._lock:
            entries = list(self._entries)
            retained = dict(self.retained)
            thresholds = {
                ct: q for ct, q in self._quantiles.items()
            }
        summaries = [
            {
                **{
                    k: e.get(k)
                    for k in (
                        "seq",
                        "traceId",
                        "index",
                        "callType",
                        "reason",
                        "seconds",
                        "thresholdSeconds",
                        "error",
                        "recordedAt",
                        "query",
                        "fingerprint",
                        "workloadRank",
                    )
                    if e.get(k) is not None
                },
                # compact result-cache verdict; the full dict (fill
                # outcome, skip reason) stays behind ?trace_id=
                **(
                    {"cache": e["resultCache"].get("outcome")}
                    if e.get("resultCache")
                    else {}
                ),
            }
            for e in reversed(entries)
        ]
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "minLatencySeconds": self.min_latency_s,
            "retained": retained,
            "entries": summaries,
            "thresholds": {
                ct: {
                    "samples": q.samples(),
                    "p95Seconds": self.threshold(ct),
                }
                for ct, q in thresholds.items()
            },
        }

    def perfetto(self, trace_id: str, node_id: str = "local") -> dict | None:
        """One retained entry's spans as Chrome trace-event JSON — the
        Perfetto export survives the tracer ring rotating the live spans
        out, because the recorder snapshotted them at retention time."""
        from pilosa_tpu.utils import tracing

        e = self.entry(trace_id)
        if e is None:
            return None
        spans_by_node = e.get("spansByNode") or {node_id: e.get("spans") or []}
        return tracing.chrome_trace_stitched(spans_by_node)
