"""Mutation-stamped cross-request result cache (docs/result-cache.md).

The wave scheduler (executor/scheduler.py) already established the
identity law this cache rides: two queries may share one answer exactly
when their single-flight dedup key — ``(index, canonical calls, shard
scope, view-version mutation stamp)`` — is equal, because every data
write bumps a view version through the globally monotone counter, so a
post-write query computes a DIFFERENT key and can never observe a
pre-write result.  Single-flight applies that law for the lifetime of
one in-flight execution and then throws the answer away; this cache
retains SETTLED results under the same key, turning the workload
plane's measured unchanged-stamp repeat traffic (docs/workload.md
cachability estimate) into serves that skip the admission lane, the
worker pool, and the engines entirely.

Two mechanisms close the gaps the stamp alone leaves:

* **Explicit invalidation** (``invalidate``): attribute writes
  (SetRowAttrs/SetColumnAttrs) mutate attribute stores WITHOUT bumping
  any view version, so a stamp-keyed entry would serve stale attrs
  forever.  Every API write path must therefore reach the invalidation
  hook (``API._invalidate_results`` — enforced by the ``cacheinvariant``
  analyzer rule), which also reclaims the unreachable old-stamp
  generations instead of waiting for LRU pressure to find them.
* **Fill generations** (``generation``/``offer(gen=...)``): a fill whose
  execution overlapped an invalidation must not resurrect a pre-write
  result — the caller snapshots the index's generation before
  executing, and the offer is refused if it moved.

Admission is cost-aware: results cheaper than ``result-cache-min-cost-
ms`` are not worth a ledger slot (the 0.2ms Count), results larger than
the per-entry byte cap would evict half the working set for one giant
answer, and an index whose stamp churns on every consecutive fill is
write-dominated — its entries would rotate out before a single hit.
Everything admitted is charged against the ``result-cache-bytes``
budget with LRU eviction, and each entry carries the route cache's
bounded revalidate-every-N countdown (executor/executor.py): after
``REVALIDATE_HITS`` serves the entry steps aside for one real
execution, so no answer — however hot — serves unverified forever.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any

from pilosa_tpu.utils import sanitize

# after this many hits an entry is deliberately served as a miss and
# dropped, so the settle path re-executes and re-fills it — the route
# cache's bounded revalidate-every-N idiom (executor/executor.py),
# sized larger because a result hit saves milliseconds where a route
# revalidation saves microseconds
REVALIDATE_HITS = 1024

# one entry may take at most budget/_ENTRY_BUDGET_FRACTION bytes: a
# single giant GroupBy must not evict the whole hot working set.  This
# cap is also the workload estimator's byte cutoff — repeats whose
# results exceed it are NOT counted as servable (docs/workload.md)
_ENTRY_BUDGET_FRACTION = 8

# consecutive offers under a CHANGED stamp before an index is treated
# as write-dominated and admission pauses until a stamp repeats
_CHURN_STREAK = 16

_SKIP_OFF = "cache-off"
_SKIP_COST = "cost-below-threshold"
_SKIP_BYTES = "over-byte-cap"
_SKIP_CHURN = "stamp-churn"
_SKIP_STALE = "invalidated-during-execution"


class _Entry:
    __slots__ = (
        "key", "index", "resp", "body", "nbytes", "cost_s", "hits",
        "countdown",
    )

    def __init__(self, key: tuple, resp: dict, body: bytes, cost_s: float):
        self.key = key
        self.index = key[0]
        self.resp = resp  # JSON-ready response dict — treated immutable
        self.body = body  # pre-serialized JSON bytes (the loop fast path)
        self.nbytes = len(body)
        self.cost_s = cost_s
        self.hits = 0
        self.countdown = REVALIDATE_HITS


class _PqlKeyer:
    """Raw pql text → canonical call-repr tuple, memoized.  The
    event-loop fast path CONSULTS only (``cached``) — it never parses:
    charging every first-seen query a parse on the serving thread is
    exactly the miss-path overhead the bench gate bounds at 3%.
    Instead the worker/coordinator paths, which parse anyway, record
    the identity (``memoize``) at settle time, so the SECOND arrival
    of a hot query is served from the loop.  Write-bearing queries
    memoize as ``None`` — the fast path steps aside permanently.
    Bounded LRU so hostile distinct queries cannot grow the memo
    without bound."""

    MISSING = object()  # "never seen": distinct from memoized None

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._lock = sanitize.make_lock("_PqlKeyer._lock", loop_safe=True)
        self._memo: OrderedDict[str, tuple | None] = OrderedDict()

    def cached(self, pql: str):
        """The memoized canonical tuple, ``None`` (a write), or
        ``MISSING`` — never parses, safe on the event loop."""
        # loop_safe: O(1) LRU memo peek, nothing blocking under the
        # lock; registered loop_safe with the sanitizer (make_lock)
        with self._lock:  # pilosa: allow(loop-purity)
            if pql in self._memo:
                self._memo.move_to_end(pql)
                return self._memo[pql]
        return self.MISSING

    def memoize(self, pql: str, canon: tuple | None) -> None:
        with self._lock:
            self._memo[pql] = canon
            self._memo.move_to_end(pql)
            while len(self._memo) > self.capacity:
                self._memo.popitem(last=False)


class ResultCache:
    """Bounded, byte-ledgered result cache keyed on the scheduler's
    single-flight dedup identity.  Thread-safe; all counters and the
    ledger live under one lock (lookups are dict hits — the lock is
    never held across parsing, execution, or serialization)."""

    def __init__(
        self,
        max_bytes: int = 64_000_000,
        min_cost_ms: float = 1.0,
        mode: str = "on",
        stats=None,
    ):
        if mode not in ("on", "off"):
            raise ValueError(
                f"result-cache-mode must be 'on' or 'off', got {mode!r}"
            )
        self.max_bytes = max(0, int(max_bytes))
        self.min_cost_ms = float(min_cost_ms)
        self.mode = mode
        self.stats = stats
        self._lock = sanitize.make_lock("ResultCache._lock", loop_safe=True)
        self._entries: OrderedDict[tuple, _Entry] = OrderedDict()
        self._by_index: dict[str, set] = {}
        self._gen: dict[str, int] = {}
        # per-index (last fill stamp, consecutive-changed streak) for
        # the write-churn admission guard
        self._stamp_seen: dict[str, tuple[Any, int]] = {}
        self.used_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.invalidated_entries = 0
        self.fills = 0
        self.revalidations = 0
        self.skips: dict[str, int] = {}
        self._keyer = _PqlKeyer()
        self._tl = threading.local()

    # ------------------------------------------------------------ config
    @property
    def enabled(self) -> bool:
        return self.mode == "on" and self.max_bytes > 0

    @property
    def entry_byte_cap(self) -> int:
        return self.max_bytes // _ENTRY_BUDGET_FRACTION

    # ------------------------------------------------------------ lookup
    def get(self, key: tuple) -> _Entry | None:
        """The settled entry for this dedup key, or None.  Counts the
        hit/miss and stamps the thread-local outcome the HTTP layer
        tags flightrec/EXPLAIN with (``consume_outcome``)."""
        if not self.enabled:
            self._set_outcome("skip", _SKIP_OFF)
            return None
        if getattr(self._tl, "bypass", 0):
            # ?profile / EXPLAIN ANALYZE: measured actuals must reflect
            # a real execution, never a cached serve
            self._set_outcome("skip", "bypass")
            return None
        # loop_safe: bounded LRU probe + counter bumps, nothing
        # blocking under the lock; registered loop_safe (make_lock)
        with self._lock:  # pilosa: allow(loop-purity)
            e = self._entries.get(key)
            if e is not None:
                e.countdown -= 1
                if e.countdown <= 0:
                    # bounded revalidate: step aside for one real
                    # execution; the settle path re-fills the key
                    self._drop_locked(e)
                    self.revalidations += 1
                    e = None
            if e is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                e.hits += 1
                self.hits += 1
        if e is None:
            if self.stats is not None:
                self.stats.count("result_cache_misses_total")
            self._set_outcome("miss")
            return None
        if self.stats is not None:
            self.stats.count("result_cache_hits_total")
        self._set_outcome("hit")
        return e

    def lookup_pql(
        self, api, index: str, pql: str, shards: list[int] | None
    ) -> _Entry | None:
        """Loop-thread fast path (server/eventloop.py): raw request →
        settled entry, or None when the worker path must run.  Pure
        CPU — two dict lookups plus the stack-token walk, NO parsing
        (the worker path's ``memoize_pql`` populated the keyer) — so it
        is legal inside the event loop's coroutine (the asyncpurity
        rule bans blocking calls, not dict lookups)."""
        if not self.enabled:
            return None
        canon = self._keyer.cached(pql)
        if canon is None or canon is _PqlKeyer.MISSING:
            # a write, or text the worker path has not settled yet —
            # either way the worker path owns this arrival
            return None
        idx = api.holder.index(index)
        if idx is None:
            return None  # unknown index: the worker path owns the 4xx
        from pilosa_tpu.executor.scheduler import stack_token

        key = (
            index,
            canon,
            tuple(shards) if shards is not None else None,
            stack_token(idx),
        )
        return self.get(key)

    def memoize_pql(self, pql: str, calls: list | None) -> None:
        """Record raw query text → canonical identity for the event-loop
        fast path.  Called from the paths that parsed the text anyway
        (API.query, Cluster.query) so the loop itself never parses;
        pass ``calls=None`` for write-bearing queries — the loop then
        steps aside for that text permanently."""
        if not self.enabled:
            return
        if calls is None:
            self._keyer.memoize(pql, None)
            return
        from pilosa_tpu.executor.scheduler import canonical_calls

        # per-call-object repr cache: the fill leg's dedup_key and the
        # scheduler's single-flight key reuse this render
        self._keyer.memoize(pql, canonical_calls(calls))

    def contains(self, key: tuple) -> bool:
        """Non-mutating peek for EXPLAIN — no counters, no LRU touch."""
        with self._lock:
            return key in self._entries

    # ------------------------------------------------------------ fill
    def generation(self, index: str) -> int:
        """The index's invalidation generation: snapshot BEFORE
        executing, hand to ``offer`` — a fill that overlapped an
        invalidation is refused instead of resurrecting a pre-write
        result under a still-current key (attr writes don't move the
        stamp, so the key alone cannot catch this race)."""
        with self._lock:
            return self._gen.get(index, 0)

    def offer(
        self, key: tuple, resp: dict, cost_s: float, gen: int | None = None
    ) -> bool:
        """Offer one settled response for admission.  ``cost_s`` is the
        measured execution cost (the admission signal); ``gen`` the
        pre-execution generation from ``generation()``."""
        if not self.enabled:
            self._set_fill(_SKIP_OFF)
            return False
        if cost_s * 1e3 < self.min_cost_ms:
            self._skip(_SKIP_COST)
            return False
        index = key[0]
        stamp = key[3] if len(key) > 3 else None
        body = json.dumps(resp, separators=(",", ":")).encode()
        if len(body) > self.entry_byte_cap:
            self._skip(_SKIP_BYTES)
            return False
        e = _Entry(key, resp, body, cost_s)
        evicted = 0
        with self._lock:
            if gen is not None and self._gen.get(index, 0) != gen:
                self._skip_locked(_SKIP_STALE)
                return False
            prev, streak = self._stamp_seen.get(index, (None, 0))
            streak = 0 if stamp == prev else streak + 1
            self._stamp_seen[index] = (stamp, streak)
            if streak >= _CHURN_STREAK:
                # write-dominated index: every recent fill arrived under
                # a fresh stamp, so admitted entries rotate out before a
                # single hit — pause admission until a stamp repeats
                self._skip_locked(_SKIP_CHURN)
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._drop_locked(old, pop=False)
            while (
                self.used_bytes + e.nbytes > self.max_bytes and self._entries
            ):
                _, victim = self._entries.popitem(last=False)
                self._drop_locked(victim, pop=False)
                self.evictions += 1
                evicted += 1
            self._entries[key] = e
            self._by_index.setdefault(index, set()).add(key)
            self.used_bytes += e.nbytes
            self.fills += 1
        if evicted and self.stats is not None:
            self.stats.count("result_cache_evictions_total", evicted)
        self._set_fill("filled")
        return True

    def _drop_locked(self, e: _Entry, pop: bool = True) -> None:
        if pop:
            self._entries.pop(e.key, None)
        keys = self._by_index.get(e.index)
        if keys is not None:
            keys.discard(e.key)
            if not keys:
                self._by_index.pop(e.index, None)
        self.used_bytes -= e.nbytes

    # ------------------------------------------------------- invalidation
    def invalidate(self, index: str) -> int:
        """Drop every entry for ``index`` and bump its fill generation.
        The write-path hook (API._invalidate_results) — correctness for
        stamp-blind attr writes, byte reclamation for everything else."""
        with self._lock:
            self._gen[index] = self._gen.get(index, 0) + 1
            self._stamp_seen.pop(index, None)
            keys = self._by_index.pop(index, set())
            dropped = 0
            for k in keys:
                e = self._entries.pop(k, None)
                if e is not None:
                    self.used_bytes -= e.nbytes
                    dropped += 1
            self.invalidations += 1
            self.invalidated_entries += dropped
        if self.stats is not None:
            self.stats.count("result_cache_invalidations_total")
        return dropped

    def clear(self) -> None:
        """Drop everything (cluster attach: single-node entries are not
        merged-topology entries, even under an unchanged local stamp)."""
        with self._lock:
            for index in list(self._by_index):
                self._gen[index] = self._gen.get(index, 0) + 1
            self._entries.clear()
            self._by_index.clear()
            self._stamp_seen.clear()
            self.used_bytes = 0

    # ------------------------------------------------------------ outcome
    @contextmanager
    def bypass(self):
        """Thread-local lookup bypass: real execution required (profile
        / EXPLAIN ANALYZE).  Fills are still allowed — a profiled run
        produces a perfectly valid settled result."""
        prev = getattr(self._tl, "bypass", 0)
        self._tl.bypass = prev + 1
        try:
            yield
        finally:
            self._tl.bypass = prev

    def _set_outcome(self, kind: str, reason: str | None = None) -> None:
        self._tl.outcome = (kind, reason)

    def _set_fill(self, what: str) -> None:
        self._tl.fill = what

    def _skip(self, reason: str) -> None:
        with self._lock:
            self._skip_locked(reason)

    def _skip_locked(self, reason: str) -> None:
        self.skips[reason] = self.skips.get(reason, 0) + 1
        self._set_fill(reason)

    def consume_outcome(self) -> dict | None:
        """This thread's last lookup/fill verdict, cleared on read — the
        HTTP settle path tags flightrec entries and the slow-query log
        with it."""
        out = getattr(self._tl, "outcome", None)
        fill = getattr(self._tl, "fill", None)
        self._tl.outcome = None
        self._tl.fill = None
        if out is None and fill is None:
            return None
        d: dict = {}
        if out is not None:
            d["outcome"] = out[0]
            if out[1]:
                d["reason"] = out[1]
        if fill is not None:
            d["fill"] = fill
        return d

    # ------------------------------------------------------------ surface
    def candidacy(self, index: str, has_write: bool) -> dict:
        """The structural half of the EXPLAIN verdict (docs/result-
        cache.md): would a settled result for this query be admitted?
        The HTTP layer adds the measured half (per-fingerprint cost and
        bytes from the workload plane) next to these."""
        if self.mode == "off":
            return {"admitted": False, "reason": "result-cache-mode is off"}
        if self.max_bytes <= 0:
            return {
                "admitted": False,
                "reason": "result-cache-bytes budget is zero",
            }
        if has_write:
            return {
                "admitted": False,
                "reason": "query contains writes (never cached)",
            }
        with self._lock:
            _, streak = self._stamp_seen.get(index, (None, 0))
        if streak >= _CHURN_STREAK:
            return {
                "admitted": False,
                "reason": (
                    f"stamp churn: {streak} consecutive fills under a "
                    "changed mutation stamp — write-dominated index"
                ),
            }
        return {
            "admitted": True,
            "reason": (
                f"read query; admitted when measured cost ≥ "
                f"{self.min_cost_ms}ms and result ≤ "
                f"{self.entry_byte_cap} bytes"
            ),
        }

    def snapshot(self) -> dict:
        """The /debug/vars ``resultCache`` section and the
        /debug/resources ledger row's source."""
        with self._lock:
            return {
                "mode": self.mode,
                "enabled": self.enabled,
                "maxBytes": self.max_bytes,
                "usedBytes": self.used_bytes,
                "entryByteCap": self.entry_byte_cap,
                "minCostMs": self.min_cost_ms,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "hitFraction": round(
                    self.hits / max(1, self.hits + self.misses), 4
                ),
                "fills": self.fills,
                "evictions": self.evictions,
                "revalidations": self.revalidations,
                "invalidations": self.invalidations,
                "invalidatedEntries": self.invalidated_entries,
                "admissionSkips": dict(self.skips),
            }
