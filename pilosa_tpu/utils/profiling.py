"""Profiling surface — the /debug/pprof analogue (VERDICT r2 missing #5).

Reference: net/http/pprof mounted in http/handler.go (profile, heap,
goroutine). Python equivalents, dependency-free:

- ``sample_profile(seconds)``: a sampling wall-clock profiler over ALL
  threads (sys._current_frames at ~100 Hz), emitting folded-stack lines
  (``a;b;c count``) directly consumable by flamegraph tooling — the
  analogue of ``/debug/pprof/profile``. Sampling, not tracing: safe to
  run against a serving process.
- ``thread_dump()``: current stack of every live thread — the analogue of
  ``/debug/pprof/goroutine?debug=2``.
- ``heap_profile(top)``: top allocation sites via tracemalloc — the
  analogue of ``/debug/pprof/heap``. tracemalloc starts on the first
  call (a line notes when tracking began; earlier allocations are
  invisible, matching pprof's sampling-from-start caveat).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


def _folded(frame) -> str:
    parts: list[str] = []
    while frame is not None:
        code = frame.f_code
        parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno})")
        frame = frame.f_back
    return ";".join(reversed(parts))


def sample_profile(seconds: float = 5.0, hz: int = 100) -> str:
    """Sample every thread's stack for ``seconds``; return folded-stack
    text sorted by sample count (one line per distinct stack)."""
    seconds = min(float(seconds), 60.0)
    interval = 1.0 / max(1, hz)
    me = threading.get_ident()
    counts: Counter[str] = Counter()
    deadline = time.perf_counter() + seconds
    n_samples = 0
    while time.perf_counter() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            counts[_folded(frame)] += 1
        n_samples += 1
        time.sleep(interval)
    lines = [f"# {n_samples} samples over {seconds:.1f}s at ~{hz} Hz"]
    for stack, n in counts.most_common():
        lines.append(f"{stack} {n}")
    return "\n".join(lines) + "\n"


class WholeRunSampler:
    """Whole-run sampling profiler over ALL threads (the server
    command's cpu-profile flag): a daemon thread samples
    sys._current_frames at ``hz`` until stop(), then writes folded-stack
    lines to ``out`` (an open text file — opened by the caller so a bad
    path fails at startup). Memory is bounded by the number of DISTINCT
    stacks, not run length."""

    def __init__(self, out, hz: int = 50):
        self._out = out
        self._interval = 1.0 / max(1, hz)
        self._counts: Counter[str] = Counter()
        self._n = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="cpu-profile-sampler", daemon=True
        )
        self._t0 = time.perf_counter()

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        me = threading.get_ident()
        while not self._stop.is_set():
            for tid, frame in sys._current_frames().items():
                if tid != me:
                    self._counts[_folded(frame)] += 1
            self._n += 1
            self._stop.wait(self._interval)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        elapsed = time.perf_counter() - self._t0
        with self._out as f:
            f.write(f"# {self._n} samples over {elapsed:.1f}s\n")
            for stack, n in self._counts.most_common():
                f.write(f"{stack} {n}\n")


def thread_dump() -> str:
    """Stack of every live thread (goroutine-dump analogue)."""
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        t = by_id.get(tid)
        name = t.name if t else f"thread-{tid}"
        daemon = " daemon" if t is not None and t.daemon else ""
        out.append(f"--- {name} (id {tid}){daemon} ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


_heap_started_at: float | None = None


def heap_profile(top: int = 50) -> dict:
    """Top allocation sites since tracking began. Starts tracemalloc on
    first use (tracking adds overhead only from then on)."""
    import tracemalloc

    global _heap_started_at
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        _heap_started_at = time.time()
        return {
            "startedAt": _heap_started_at,
            "note": "tracemalloc started now; call again for allocations",
            "top": [],
        }
    snapshot = tracemalloc.take_snapshot()
    stats = snapshot.statistics("lineno")[: int(top)]
    current, peak = tracemalloc.get_traced_memory()
    return {
        "startedAt": _heap_started_at,
        "currentBytes": current,
        "peakBytes": peak,
        "top": [
            {
                "site": str(s.traceback[0]) if s.traceback else "?",
                "bytes": s.size,
                "count": s.count,
            }
            for s in stats
        ],
    }
