"""Metrics: counters/gauges/timers with expvar-style JSON and Prometheus
text exposition.

Reference: stats.go (StatsClient interface with tags), stats/ adapters
(statsd/expvar) and the /metrics Prometheus route. One in-process registry
replaces the adapter zoo; both wire formats read from it.
"""

from __future__ import annotations

import bisect
import threading
import time

from pilosa_tpu.utils import sanitize
from collections import defaultdict


def _log_buckets() -> tuple[float, ...]:
    """Log-spaced latency boundaries, 1-2.5-5 per decade from 100 µs to
    500 s — ~3 buckets/decade keeps quantile error within the decade
    step while spanning sub-ms kernel dispatches through wedged-device
    timeouts. Roughly the Prometheus client default, extended down."""
    out = []
    for exp in range(-4, 3):
        for mant in (1.0, 2.5, 5.0):
            out.append(mant * 10.0**exp)
    return tuple(out)


DEFAULT_BUCKETS = _log_buckets()


def _count_buckets() -> tuple[float, ...]:
    """Power-of-two boundaries for COUNT distributions (wave occupancy,
    batch sizes): 1..4096 — small counts resolve exactly, large ones to
    within a factor of two."""
    return tuple(float(1 << i) for i in range(13))


COUNT_BUCKETS = _count_buckets()


# one-line HELP strings for the exposition format, keyed by family name
# minus the ``pilosa_tpu_`` prefix; families not listed here get a
# generic line (the metric⇄docs drift analyzer rule keeps the REAL
# catalog in docs/observability.md complete — this dict only feeds the
# human-readable scrape output)
_METRIC_HELP = {
    "http_requests": "requests per HTTP route",
    "http_request_seconds": "per-route HTTP handler latency",
    "query_seconds": "end-to-end /index/{i}/query latency",
    "executor_call_seconds": "per-PQL-call dispatch time in the local executor",
    "executor_readback_seconds": "the one device-to-host readback wave per request",
    "fanout_rpc_seconds": "coordinator-to-peer query RPC latency per leg",
    "fanout_batch_rpc_seconds": "coalesced multi-query fan-out RPC latency",
    "internal_query_batch_seconds": "serve time of /internal/query/batch",
    "queries_routed": "read calls per engine picked by the cost router",
    "queries_served": "read legs this node executed",
    "queries_gated": "queries arriving during the device-probe window",
    "queries_deduped": "queries answered by single-flight dedup",
    "queries_partial": "queries answered with partial results",
    "queries_rejected": "requests shed by admission control",
    "queries_per_wave": "occupancy of cross-query device waves",
    "wave_flush_reason": "why each wave dispatched",
    "legs_per_batch_rpc": "legs coalesced per multi-query fan-out RPC",
    "legs_failed_over": "fan-out legs re-planned onto a surviving replica",
    "rpc_retries": "idempotent RPC retry attempts",
    "rpc_backpressure": "RPCs answered 429 by a peer's admission control",
    "breaker_state": "per-peer circuit breaker state (0 closed, 1 open, 2 half-open)",
    "connections_open": "open HTTP connections on the event front end",
    "connections_accepted": "accepted HTTP connections",
    "connections_aborted_midbody": "connections torn down mid-request-body",
    "admission_queue_depth": "admission queue depth at arrival, per class",
    "admission_wait_seconds": "time spent queued in admission, per class",
    "eventloop_unhandled_exceptions": "exceptions nothing awaited (bugs)",
    "compaction_pending": "queued plus in-flight background compactions",
    "compactions_total": "completed background compactions",
    "compactions_failed": "compactions aborted by a disk error",
    "compactions_crashed": "compactions torn by an injected crash",
    "stack_evictions_total": "device-cache evictions under the byte budget",
    "rows_promoted": "rows promoted into tiered compressed residency",
    "rows_demoted": "resident rows LRU-demoted back to host-only serving",
    "residency_bytes": "device bytes held by tiered container stores",
    "flightrec_retained_total": "queries retained by the flight recorder",
    "profiler_samples_total": "stack samples taken by the continuous profiler",
    "eventloop_lag_seconds": "scheduled-callback wakeup delay on the event loop",
    "gil_wait_seconds": "cross-thread no-op wakeup overshoot (GIL-contention estimate)",
    "worker_utilization": "sampled in-flight/limit fraction per admission class",
    "lock_wait_seconds": "time blocked acquiring a contended hot lock, per family",
    "lock_contended_total": "contended acquires per hot-lock family",
    "resource_pressure": "used/limit fraction per resource-ledger subsystem",
    "resource_bytes": "bytes used per resource-ledger subsystem",
    "router_misroute_total": "settled queries whose measured cost exceeded another route's estimate",
    "router_estimate_error_ratio": "measured over estimated cost for the chosen route",
    "workload_observed_total": "settled public queries observed by the workload plane",
    "workload_sampled_total": "queries recorded into the workload capture ring",
    "workload_fingerprints_tracked": "distinct fingerprints held by the heavy-hitter sketch",
    "workload_spill_segments": "workload capture spill segments on disk",
    "slo_burn_rate": "error-budget burn rate per call type and window (1.0 = spending exactly the budget)",
    "slo_budget_remaining": "fraction of the error budget left over the longest SLO window",
}


class Ewma:
    """Exponentially weighted moving average — the calibration primitive
    behind the query router's online crossover (executor/router.py): the
    first observation seeds the value, later ones fold in with weight
    ``alpha``.  Thread-safe the cheap way: ``update`` races lose an
    observation at worst, never corrupt the float."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.3, value: float | None = None):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.value = value

    def update(self, x: float) -> float:
        v = self.value
        self.value = x if v is None else v + self.alpha * (x - v)
        return self.value


class Histogram:
    """Log-bucketed latency histogram with percentile snapshots and
    Prometheus ``_bucket``/``_sum``/``_count`` exposition (reference:
    the statsd adapter's Histogram/Timing fed per-tag distributions;
    here the in-process registry keeps the distribution itself so
    p50/p95/p99 are readable without a statsd backend). Thread-safe:
    ``observe`` takes a per-histogram lock, so concurrent HTTP handler
    threads never lose increments."""

    __slots__ = ("buckets", "counts", "count", "sum", "_lock")

    def __init__(self, buckets: tuple[float, ...] | None = None):
        self.buckets = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
        # counts[i] observations ≤ buckets[i]; counts[-1] is the +Inf bucket
        self.counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self._lock = sanitize.make_lock("Histogram._lock", loop_safe=True)

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += value

    def percentile(self, q: float) -> float:
        """Approximate q-quantile (q in [0, 1]) by linear interpolation
        within the containing bucket — same estimator as PromQL's
        histogram_quantile, so dashboards and snapshots agree. Returns
        the largest finite boundary for observations in +Inf."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.buckets):  # +Inf bucket
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return self.buckets[-1]

    def totals(self) -> tuple[int, float]:
        """(count, sum) under one lock acquisition — the exposition path
        reads these per scrape and must not pay for percentiles."""
        with self._lock:
            return self.count, self.sum

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self.count, self.sum
        return {
            "count": count,
            "totalSeconds": total,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative_count), ...] ending at (inf, count) — the
        Prometheus exposition shape."""
        with self._lock:
            counts = list(self.counts)
        out = []
        cum = 0
        for le, c in zip(self.buckets, counts):
            cum += c
            out.append((le, cum))
        out.append((float("inf"), cum + counts[-1]))
        return out


class StatsClient:
    def __init__(self, prefix: str = "pilosa_tpu"):
        self.prefix = prefix
        self._lock = sanitize.make_lock("StatsClient._lock", loop_safe=True)
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._timings: dict[tuple, Histogram] = {}
        # non-latency value distributions (queries_per_wave): same
        # Histogram machinery, count-shaped buckets, no _seconds suffix
        self._dists: dict[tuple, Histogram] = {}

    @staticmethod
    def _key(name: str, tags: dict | None) -> tuple:
        return (name, tuple(sorted((tags or {}).items())))

    def count(self, name: str, value: float = 1, tags: dict | None = None) -> None:
        with self._lock:
            self._counters[self._key(name, tags)] += value

    def gauge(self, name: str, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._gauges[self._key(name, tags)] = value

    def timing(self, name: str, seconds: float, tags: dict | None = None) -> None:
        key = self._key(name, tags)
        with self._lock:
            hist = self._timings.get(key)
            if hist is None:
                hist = self._timings[key] = Histogram()
        hist.observe(seconds)

    def observe(
        self,
        name: str,
        value: float,
        tags: dict | None = None,
        buckets: tuple[float, ...] | None = None,
    ) -> None:
        """Record into a VALUE distribution (e.g. ``queries_per_wave``):
        a real histogram like timing(), but with count-shaped buckets
        and no seconds unit.  ``buckets`` overrides the boundary set at
        series creation (e.g. the router audit's error-RATIO
        distribution needs sub-1.0 resolution the power-of-two count
        buckets can't give); later calls reuse whatever the series was
        created with."""
        key = self._key(name, tags)
        with self._lock:
            hist = self._dists.get(key)
            if hist is None:
                hist = self._dists[key] = Histogram(buckets or COUNT_BUCKETS)
        hist.observe(value)

    def histogram(self, name: str, tags: dict | None = None) -> Histogram | None:
        """The live Histogram behind a timer series (tests, bench, and
        the profile surface read percentiles through this)."""
        with self._lock:
            return self._timings.get(self._key(name, tags))

    def distribution(self, name: str, tags: dict | None = None) -> Histogram | None:
        """The live Histogram behind a value-distribution series
        (bench reads queries_per_wave percentiles through this)."""
        with self._lock:
            return self._dists.get(self._key(name, tags))

    def close(self) -> None:
        """Release emission resources (no-op for registry-only clients)."""

    def timer(self, name: str, tags: dict | None = None):
        """Context manager recording elapsed seconds."""
        client = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                client.timing(name, time.perf_counter() - self.t0, tags)
                return False

        return _Timer()

    # ------------------------------------------------------------- output
    def expvar(self) -> dict:
        """JSON snapshot (reference: /debug/vars)."""
        with self._lock:
            fmt = lambda k: k[0] + (
                "{" + ",".join(f"{t}={v}" for t, v in k[1]) + "}" if k[1] else ""
            )
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timings = dict(self._timings)
            dists = dict(self._dists)
        out = {
            "counters": {fmt(k): v for k, v in counters.items()},
            "gauges": {fmt(k): v for k, v in gauges.items()},
            "timings": {fmt(k): h.snapshot() for k, h in timings.items()},
        }
        if dists:
            out["distributions"] = {
                fmt(k): h.snapshot() for k, h in dists.items()
            }
        return out

    def _timing_family(self, name: str) -> str:
        """Timer series name → Prometheus metric family: the _seconds
        unit suffix is appended once (call sites already named the hot
        timers *_seconds)."""
        base = f"{self.prefix}_{name}"
        return base if name.endswith("_seconds") else base + "_seconds"

    @staticmethod
    def _escape_label(value) -> str:
        """Exposition-format label-value escaping: backslash, double
        quote, and newline must be escaped or a value containing any of
        them corrupts every scrape after it."""
        return (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    def _help_text(self, family: str, kind: str) -> str:
        base = family[len(self.prefix) + 1 :] if family.startswith(
            self.prefix + "_"
        ) else family
        return _METRIC_HELP.get(base, f"pilosa-tpu {kind} {base}")

    def prometheus(self) -> str:
        """Prometheus text exposition (reference: /metrics), conformant
        with the exposition format: one ``# HELP`` + ``# TYPE`` pair per
        metric family (not per series), label values escaped.  Timers
        expose as real histograms — cumulative ``_bucket{le=...}`` series
        plus ``_sum``/``_count`` — so p95/p99 are PromQL-derivable."""
        lines = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            timings = sorted(self._timings.items())
            dists = sorted(self._dists.items())

        def labels(k, extra: str = ""):
            inner = ",".join(
                f'{t}="{self._escape_label(v)}"' for t, v in k[1]
            )
            if extra:
                inner = f"{inner},{extra}" if inner else extra
            return "{" + inner + "}" if inner else ""

        seen_families = set()

        def header(family: str, kind: str) -> None:
            if family in seen_families:
                return
            seen_families.add(family)
            lines.append(f"# HELP {family} {self._help_text(family, kind)}")
            lines.append(f"# TYPE {family} {kind}")

        for k, v in counters:
            family = f"{self.prefix}_{k[0]}"
            header(family, "counter")
            lines.append(f"{family}{labels(k)} {v}")
        for k, v in gauges:
            family = f"{self.prefix}_{k[0]}"
            header(family, "gauge")
            lines.append(f"{family}{labels(k)} {v}")
        # distributions expose under their bare name (no _seconds unit)
        series = [(self._timing_family(k[0]), k, h) for k, h in timings] + [
            (f"{self.prefix}_{k[0]}", k, h) for k, h in dists
        ]
        for family, k, hist in series:
            header(family, "histogram")
            for le, cum in hist.cumulative():
                le_str = "+Inf" if le == float("inf") else f"{le:g}"
                le_label = labels(k, f'le="{le_str}"')
                lines.append(f"{family}_bucket{le_label} {cum}")
            count, total = hist.totals()
            lines.append(f"{family}_sum{labels(k)} {total}")
            lines.append(f"{family}_count{labels(k)} {count}")
        return "\n".join(lines) + "\n"


class StatsdStats(StatsClient):
    """StatsClient that ALSO emits each update as a statsd datagram
    (reference: stats/statsd adapter). Datagram format is classic statsd
    with dogstatsd-style ``|#tag:value`` tags; UDP, fire-and-forget —
    emission failures never affect the serving path. The in-process
    registry still accumulates, so /metrics and /debug/vars keep
    working alongside."""

    def __init__(self, host: str, port: int, prefix: str = "pilosa_tpu"):
        super().__init__(prefix=prefix)
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        # resolve ONCE here — sendto with a hostname would do a
        # synchronous DNS lookup per metric, in the request path
        self._sock.connect((host, port))

    @staticmethod
    def _num(value: float) -> str:
        # plain decimal only: %g's scientific notation for >=1e6 is
        # dropped by strict statsd parsers
        if float(value).is_integer():
            return str(int(value))
        return f"{value:.6f}".rstrip("0").rstrip(".")

    def _emit(self, name: str, value: str, kind: str, tags: dict | None) -> None:
        msg = f"{self.prefix}.{name}:{value}|{kind}"
        if tags:
            msg += "|#" + ",".join(f"{t}:{v}" for t, v in sorted(tags.items()))
        try:
            self._sock.send(msg.encode())
        except OSError:
            pass

    def count(self, name: str, value: float = 1, tags: dict | None = None) -> None:
        super().count(name, value, tags)
        self._emit(name, self._num(value), "c", tags)

    def gauge(self, name: str, value: float, tags: dict | None = None) -> None:
        super().gauge(name, value, tags)
        self._emit(name, self._num(value), "g", tags)

    def timing(self, name: str, seconds: float, tags: dict | None = None) -> None:
        super().timing(name, seconds, tags)
        self._emit(name, self._num(seconds * 1e3), "ms", tags)

    def observe(
        self,
        name: str,
        value: float,
        tags: dict | None = None,
        buckets: tuple[float, ...] | None = None,
    ) -> None:
        # value distributions (queries_per_wave, legs_per_batch_rpc)
        # emit as dogstatsd histograms — "every update" includes these
        super().observe(name, value, tags, buckets)
        self._emit(name, self._num(value), "h", tags)

    def close(self) -> None:
        self._sock.close()


def make_stats(service: str, statsd_host: str = "") -> StatsClient:
    """Factory from config: ``metric_service`` = prometheus (registry,
    read by /metrics and /debug/vars), statsd (registry + UDP emission
    to ``statsd_host`` as host:port), or none. Misconfiguration raises —
    a silently inert metrics setup is only discovered when dashboards
    stay empty."""
    if service == "statsd":
        if not statsd_host:
            raise ValueError(
                "metric_service = 'statsd' requires statsd_host (host:port)"
            )
        host, sep, port = statsd_host.rpartition(":")
        if not sep:
            host, port = statsd_host, "8125"
        try:
            return StatsdStats(host or "127.0.0.1", int(port))
        except (ValueError, OSError) as e:
            raise ValueError(f"bad statsd_host {statsd_host!r}: {e}") from e
    if service in ("", "none", "nop"):
        return NopStats()
    if service != "prometheus":
        raise ValueError(
            f"unknown metric_service {service!r}; use prometheus, statsd, or none"
        )
    return StatsClient()


class NopStats(StatsClient):
    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass


class IngestMeter:
    """Rolling ingest-throughput accounting (docs/ingest.md): lifetime
    totals plus a sliding-window rate, read by the /debug/resources
    "ingest" row so an operator can see sustained Mbit/s without
    scraping counters twice and differencing. Window math is monotonic
    throughout."""

    WINDOW_S = 60.0

    def __init__(self) -> None:
        self._lock = sanitize.make_lock("IngestMeter._lock")
        self.bytes_total = 0
        self.bits_total = 0
        self.posts_total = 0
        self._events: list[tuple[float, int, int]] = []

    def record(self, nbytes: int, bits: int = 0) -> None:
        now = time.monotonic()
        with self._lock:
            self.bytes_total += nbytes
            self.bits_total += bits
            self.posts_total += 1
            self._events.append((now, nbytes, bits))
            self._trim(now)

    def _trim(self, now: float) -> None:
        cut = now - self.WINDOW_S
        i = bisect.bisect_right(self._events, (cut, 1 << 62, 1 << 62))
        if i:
            del self._events[:i]

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            if self._events:
                span = max(now - self._events[0][0], 1e-9)
                wb = sum(e[1] for e in self._events)
                wbits = sum(e[2] for e in self._events)
            else:
                span, wb, wbits = 0.0, 0, 0
            return {
                "bytesTotal": self.bytes_total,
                "bitsTotal": self.bits_total,
                "postsTotal": self.posts_total,
                "windowSeconds": round(min(span, self.WINDOW_S), 3),
                "recentBytesPerS": round(wb / span, 1) if span else 0.0,
                "recentMbitSetPerS": (
                    round(wbits / span / 1e6, 4) if span else 0.0
                ),
            }
