"""Metrics: counters/gauges/timers with expvar-style JSON and Prometheus
text exposition.

Reference: stats.go (StatsClient interface with tags), stats/ adapters
(statsd/expvar) and the /metrics Prometheus route. One in-process registry
replaces the adapter zoo; both wire formats read from it.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class StatsClient:
    def __init__(self, prefix: str = "pilosa_tpu"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._timings: dict[tuple, list] = defaultdict(lambda: [0, 0.0])

    @staticmethod
    def _key(name: str, tags: dict | None) -> tuple:
        return (name, tuple(sorted((tags or {}).items())))

    def count(self, name: str, value: float = 1, tags: dict | None = None) -> None:
        with self._lock:
            self._counters[self._key(name, tags)] += value

    def gauge(self, name: str, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._gauges[self._key(name, tags)] = value

    def timing(self, name: str, seconds: float, tags: dict | None = None) -> None:
        with self._lock:
            entry = self._timings[self._key(name, tags)]
            entry[0] += 1
            entry[1] += seconds

    def close(self) -> None:
        """Release emission resources (no-op for registry-only clients)."""

    def timer(self, name: str, tags: dict | None = None):
        """Context manager recording elapsed seconds."""
        client = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                client.timing(name, time.perf_counter() - self.t0, tags)
                return False

        return _Timer()

    # ------------------------------------------------------------- output
    def expvar(self) -> dict:
        """JSON snapshot (reference: /debug/vars)."""
        with self._lock:
            fmt = lambda k: k[0] + (
                "{" + ",".join(f"{t}={v}" for t, v in k[1]) + "}" if k[1] else ""
            )
            return {
                "counters": {fmt(k): v for k, v in self._counters.items()},
                "gauges": {fmt(k): v for k, v in self._gauges.items()},
                "timings": {
                    fmt(k): {"count": c, "totalSeconds": s}
                    for k, (c, s) in self._timings.items()
                },
            }

    def prometheus(self) -> str:
        """Prometheus text exposition (reference: /metrics)."""
        lines = []
        with self._lock:
            def labels(k):
                if not k[1]:
                    return ""
                inner = ",".join(f'{t}="{v}"' for t, v in k[1])
                return "{" + inner + "}"

            for k, v in sorted(self._counters.items()):
                lines.append(f"# TYPE {self.prefix}_{k[0]} counter")
                lines.append(f"{self.prefix}_{k[0]}{labels(k)} {v}")
            for k, v in sorted(self._gauges.items()):
                lines.append(f"# TYPE {self.prefix}_{k[0]} gauge")
                lines.append(f"{self.prefix}_{k[0]}{labels(k)} {v}")
            for k, (c, s) in sorted(self._timings.items()):
                base = f"{self.prefix}_{k[0]}"
                lines.append(f"# TYPE {base}_seconds summary")
                lines.append(f"{base}_seconds_count{labels(k)} {c}")
                lines.append(f"{base}_seconds_sum{labels(k)} {s}")
        return "\n".join(lines) + "\n"


class StatsdStats(StatsClient):
    """StatsClient that ALSO emits each update as a statsd datagram
    (reference: stats/statsd adapter). Datagram format is classic statsd
    with dogstatsd-style ``|#tag:value`` tags; UDP, fire-and-forget —
    emission failures never affect the serving path. The in-process
    registry still accumulates, so /metrics and /debug/vars keep
    working alongside."""

    def __init__(self, host: str, port: int, prefix: str = "pilosa_tpu"):
        super().__init__(prefix=prefix)
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        # resolve ONCE here — sendto with a hostname would do a
        # synchronous DNS lookup per metric, in the request path
        self._sock.connect((host, port))

    @staticmethod
    def _num(value: float) -> str:
        # plain decimal only: %g's scientific notation for >=1e6 is
        # dropped by strict statsd parsers
        if float(value).is_integer():
            return str(int(value))
        return f"{value:.6f}".rstrip("0").rstrip(".")

    def _emit(self, name: str, value: str, kind: str, tags: dict | None) -> None:
        msg = f"{self.prefix}.{name}:{value}|{kind}"
        if tags:
            msg += "|#" + ",".join(f"{t}:{v}" for t, v in sorted(tags.items()))
        try:
            self._sock.send(msg.encode())
        except OSError:
            pass

    def count(self, name: str, value: float = 1, tags: dict | None = None) -> None:
        super().count(name, value, tags)
        self._emit(name, self._num(value), "c", tags)

    def gauge(self, name: str, value: float, tags: dict | None = None) -> None:
        super().gauge(name, value, tags)
        self._emit(name, self._num(value), "g", tags)

    def timing(self, name: str, seconds: float, tags: dict | None = None) -> None:
        super().timing(name, seconds, tags)
        self._emit(name, self._num(seconds * 1e3), "ms", tags)

    def close(self) -> None:
        self._sock.close()


def make_stats(service: str, statsd_host: str = "") -> StatsClient:
    """Factory from config: ``metric_service`` = prometheus (registry,
    read by /metrics and /debug/vars), statsd (registry + UDP emission
    to ``statsd_host`` as host:port), or none. Misconfiguration raises —
    a silently inert metrics setup is only discovered when dashboards
    stay empty."""
    if service == "statsd":
        if not statsd_host:
            raise ValueError(
                "metric_service = 'statsd' requires statsd_host (host:port)"
            )
        host, sep, port = statsd_host.rpartition(":")
        if not sep:
            host, port = statsd_host, "8125"
        try:
            return StatsdStats(host or "127.0.0.1", int(port))
        except (ValueError, OSError) as e:
            raise ValueError(f"bad statsd_host {statsd_host!r}: {e}") from e
    if service in ("", "none", "nop"):
        return NopStats()
    if service != "prometheus":
        raise ValueError(
            f"unknown metric_service {service!r}; use prometheus, statsd, or none"
        )
    return StatsClient()


class NopStats(StatsClient):
    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass
