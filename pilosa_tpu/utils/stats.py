"""Metrics: counters/gauges/timers with expvar-style JSON and Prometheus
text exposition.

Reference: stats.go (StatsClient interface with tags), stats/ adapters
(statsd/expvar) and the /metrics Prometheus route. One in-process registry
replaces the adapter zoo; both wire formats read from it.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class StatsClient:
    def __init__(self, prefix: str = "pilosa_tpu"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = defaultdict(float)
        self._gauges: dict[tuple, float] = {}
        self._timings: dict[tuple, list] = defaultdict(lambda: [0, 0.0])

    @staticmethod
    def _key(name: str, tags: dict | None) -> tuple:
        return (name, tuple(sorted((tags or {}).items())))

    def count(self, name: str, value: float = 1, tags: dict | None = None) -> None:
        with self._lock:
            self._counters[self._key(name, tags)] += value

    def gauge(self, name: str, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._gauges[self._key(name, tags)] = value

    def timing(self, name: str, seconds: float, tags: dict | None = None) -> None:
        with self._lock:
            entry = self._timings[self._key(name, tags)]
            entry[0] += 1
            entry[1] += seconds

    def timer(self, name: str, tags: dict | None = None):
        """Context manager recording elapsed seconds."""
        client = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                client.timing(name, time.perf_counter() - self.t0, tags)
                return False

        return _Timer()

    # ------------------------------------------------------------- output
    def expvar(self) -> dict:
        """JSON snapshot (reference: /debug/vars)."""
        with self._lock:
            fmt = lambda k: k[0] + (
                "{" + ",".join(f"{t}={v}" for t, v in k[1]) + "}" if k[1] else ""
            )
            return {
                "counters": {fmt(k): v for k, v in self._counters.items()},
                "gauges": {fmt(k): v for k, v in self._gauges.items()},
                "timings": {
                    fmt(k): {"count": c, "totalSeconds": s}
                    for k, (c, s) in self._timings.items()
                },
            }

    def prometheus(self) -> str:
        """Prometheus text exposition (reference: /metrics)."""
        lines = []
        with self._lock:
            def labels(k):
                if not k[1]:
                    return ""
                inner = ",".join(f'{t}="{v}"' for t, v in k[1])
                return "{" + inner + "}"

            for k, v in sorted(self._counters.items()):
                lines.append(f"# TYPE {self.prefix}_{k[0]} counter")
                lines.append(f"{self.prefix}_{k[0]}{labels(k)} {v}")
            for k, v in sorted(self._gauges.items()):
                lines.append(f"# TYPE {self.prefix}_{k[0]} gauge")
                lines.append(f"{self.prefix}_{k[0]}{labels(k)} {v}")
            for k, (c, s) in sorted(self._timings.items()):
                base = f"{self.prefix}_{k[0]}"
                lines.append(f"# TYPE {base}_seconds summary")
                lines.append(f"{base}_seconds_count{labels(k)} {c}")
                lines.append(f"{base}_seconds_sum{labels(k)} {s}")
        return "\n".join(lines) + "\n"


class NopStats(StatsClient):
    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass
