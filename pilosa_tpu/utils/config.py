"""Configuration: TOML file + PILOSA_TPU_* env vars + CLI flags.

Reference: server/config.go (three-layer TOML + PILOSA_* env + pflags;
`pilosa config` prints the effective config, generate-config emits a
template). Same precedence: flags > env > file > defaults.
"""

from __future__ import annotations

import os

try:
    import tomllib
except ImportError:  # Python < 3.11: the stdlib module's PyPI ancestor
    import tomli as tomllib
from dataclasses import dataclass, field, fields


@dataclass
class Config:
    bind: str = "127.0.0.1:10101"
    data_dir: str = "~/.pilosa_tpu"
    # cluster
    name: str = ""  # node id; derived from bind when empty
    coordinator: bool = False
    seeds: list[str] = field(default_factory=list)  # peer URIs
    replica_n: int = 1
    # background loops
    anti_entropy_interval: float = 600.0  # seconds; 0 disables
    heartbeat_interval: float = 2.0  # peer liveness probe period
    diagnostics_interval: float = 3600.0  # snapshot period; 0 disables
    # serving front end (docs/serving.md): "event" = the asyncio
    # accept/read/write loop with keep-alive multiplexing and bounded
    # admission (the default); "threaded" = the legacy thread-per-
    # request listener (rollback / latency-baseline only — no admission
    # control)
    serving_mode: str = "event"
    # open-connection cap for the event front end (0 = unlimited);
    # connections past it get 503 + Retry-After at accept
    max_connections: int = 0
    # bounded admission wait queue PER CLASS (query/write/control); a
    # request arriving with the class queue full gets 429 + Retry-After
    # instead of parking (0 = unbounded — not recommended)
    admission_queue_depth: int = 256
    # seconds an idle keep-alive connection is held before the server
    # closes it (0 = never reap)
    keepalive_idle_s: float = 75.0
    # seconds a client gets to deliver a request head or body once it
    # starts one — the slowloris cut (0 disables; also the TLS
    # handshake timeout on the event front end)
    request_read_timeout_s: float = 10.0
    # query-class worker threads for the event front end (execution
    # stays on a bounded pool; the event loop only owns I/O and
    # admission). 0 = auto: max(32, min(64, 4x cores)) — sized to wave
    # occupancy, not cores: query workers park as wave followers or in
    # GIL-released device calls. The write class gets half, control a
    # quarter (min 4).
    http_worker_threads: int = 0
    # limits
    max_writes_per_request: int = 5000
    long_query_time: float = 0.0  # seconds; log slower queries (0 = off)
    log_path: str = ""  # append server log lines to a file ("" = stderr)
    # device mesh (serving-path SPMD over all local devices)
    mesh_enabled: bool = True
    mesh_words_axis: int = 1  # >1 splits the packed word dim across devices
    # seconds to wait for the accelerator backend to prove healthy (a
    # fresh-subprocess probe) before pinning this process to the CPU
    # backend: a wedged device transport otherwise hangs the FIRST query
    # indefinitely inside backend init. 0 disables the probe (trust the
    # accelerator to come up).
    device_init_timeout: float = 300.0
    # seconds a query/import arriving DURING the device probe window
    # waits for the verdict before being served 503 + Retry-After (the
    # probe gate keeps first JAX use off a possibly-wedged backend; see
    # Server._query_gate). 0 = never wait, 503 immediately while probing.
    query_gate_wait: float = 60.0
    # multi-host process group (jax.distributed; reference analogue:
    # gossip seeds — here membership is static). Setting
    # coordinator_address makes Server.open() join the group before any
    # backend init; with >1 process the serving mesh spans all hosts via
    # multihost.make_multihost_mesh (words axis stays within one host's
    # ICI). Recipe, on each host h of N:
    #   coordinator_address = "host0:8476"
    #   num_processes = N
    #   process_id = h
    coordinator_address: str = ""
    num_processes: int = 0  # 0 = let jax.distributed infer
    process_id: int = -1  # -1 = let jax.distributed infer
    # query routing (docs/query-routing.md): per-call host/device
    # routing by a calibrated cost model. "auto" compares estimated work
    # against the online crossover; "host"/"device" pin every read to
    # one engine (the server also pins "host" when the device probe
    # fails — the degraded engine must not pay device dispatch).
    route_mode: str = "auto"  # auto | host | device
    # device stack budget in bytes — the aggregate cap on resident query
    # stacks (dense stacks + hot-row slots + tiered container stores;
    # docs/device-residency.md). 0 = auto: the legacy
    # PILOSA_TPU_STACK_BUDGET env override if set, else 70% of the
    # device's reported HBM limit, else 2 GiB.
    device_stack_budget_bytes: int = 0
    # >0 pins the crossover (words of packed-bitmap work below which a
    # read runs on the host); 0 derives it from the calibrated model
    route_crossover_words: float = 0.0
    # cost-model seeds, refined online by EWMAs over measured calls
    route_dispatch_ms: float = 1.0  # device dispatch overhead seed
    route_readback_ms: float = 2.0  # device→host readback latency seed
    route_device_words_per_s: float = 25e9  # device scan roofline
    # mesh (explicit-SPMD) route seeds — the third router path
    # (docs/spmd.md): shard_map dispatch overhead and collective-readback
    # latency, refined online like the device seeds; the scan term
    # divides by the attached mesh's device count
    route_mesh_dispatch_ms: float = 2.0
    route_mesh_readback_ms: float = 2.0
    # seconds a persisted device-probe verdict stays valid: within the
    # TTL the next boot (or bench run) reuses it instead of paying the
    # full device-init-timeout probe against a known-wedged transport
    device_probe_ttl: float = 900.0
    # cross-query wave coalescing (docs/query-batching.md): concurrent
    # sync device-routed queries share one dispatch+readback wave.
    # "adaptive" opens a straggler window only under observed
    # concurrency; "always" waits the full window per wave; "off"
    # restores the one-wave-per-request path.
    batch_mode: str = "adaptive"  # off | adaptive | always
    # microseconds the wave leader holds the wave open for stragglers
    # (the adaptive mode additionally caps this at half the readback-RTT
    # EWMA, so a local device never waits longer than its RTT is worth)
    batch_window_us: float = 250.0
    # queries per wave before an immediate flush
    batch_max_queries: int = 64
    # fault tolerance (docs/fault-tolerance.md)
    # per-query time budget in milliseconds (0 = unlimited): propagated
    # across fan-out hops via X-Pilosa-Deadline-Ms with the REMAINING
    # budget, bounding socket timeouts, retries, and wave waits;
    # exhaustion returns HTTP 504
    query_timeout_ms: float = 0.0
    # extra attempts (after the first) for idempotent node→node RPCs —
    # reads, status probes, anti-entropy pulls; never writes/imports.
    # 0 disables retries.
    rpc_retries: int = 2
    # capped exponential backoff with full jitter between retries:
    # delay ~ U(0, min(cap, base * 2^attempt))
    rpc_backoff_base_ms: float = 20.0
    rpc_backoff_cap_ms: float = 500.0
    # per-peer circuit breaker: after `threshold` consecutive RPC
    # failures the peer fast-fails (one BreakerOpenError instead of a
    # data-plane timeout per query) until a `cooldown` half-open probe
    # or a successful heartbeat closes it
    breaker_enabled: bool = True
    breaker_failure_threshold: int = 3
    breaker_cooldown_ms: float = 5000.0
    # deterministic fault injection (chaos rehearsal): a JSON list of
    # rules applied to this node's OUTGOING data-plane RPCs, seeded for
    # reproducibility; also settable at runtime via /debug/faults
    fault_rules: str = ""
    fault_seed: int = 0
    # filesystem fault injection (docs/durability.md): a JSON list of
    # rules applied to the durable write protocol's primitives (ops-log
    # appends, snapshot writes, fsyncs, renames, dir-fsyncs), seeded by
    # the shared fault-seed; drives the disk-fault chaos suite
    fs_fault_rules: str = ""
    # movement admission lane (docs/resize.md): bulk data movement —
    # rebalance pulls, anti-entropy handoff pushes, restore adopts —
    # holds one of this many concurrent transfer slots, so a resize
    # can't monopolize the node's threads or the peer's import lane
    movement_max_concurrent: int = 4
    # aggregate movement byte-rate ceiling in megabits/second (token
    # bucket with 1 s of burst); 0 = line rate. Lets an operator drain
    # a node without starving serving traffic of bandwidth.
    movement_max_mbit: float = 0.0
    # durability (docs/durability.md): when an ops-log append becomes
    # durable relative to the write acknowledgement. "always" fsyncs
    # inside every append; "batch" group-fsyncs all dirty WAL files once
    # at the request's acknowledgement barrier (the default — group
    # commit); "off" never fsyncs (page-cache-only, acknowledged writes
    # can die with the OS)
    wal_fsync_mode: str = "batch"
    # background ops-log→snapshot compaction worker threads per holder
    compaction_workers: int = 1
    # queued+in-flight compactions past which the event front end's
    # write lane answers 429 + Retry-After instead of growing the
    # ops logs (and crash-replay time) without bound; 0 = no limit
    compaction_max_debt: int = 64
    # concurrent fragment opens (snapshot deserialize + ops-log replay)
    # during Holder.open — restart-to-serving is bounded by the slowest
    # fragment, not the sum; <=1 loads serially. Device upload stays
    # lazy (first query per stack) either way.
    holder_load_workers: int = 8
    # fragment-count floor below which Holder.open loads serially even
    # with workers configured: at small counts pool spin-up costs more
    # than it overlaps (BENCH_INGEST_r08: parallel 0.159s vs serial
    # 0.066s over 12 fragments). 0 always parallelizes.
    holder_load_min_fragments: int = 32
    # flight recorder (docs/observability.md): always-on tail-based
    # retention of slow/errored query evidence, served by GET
    # /debug/flightrec. Disabling it removes the retention decision from
    # the settle path entirely (the bench's instrumented-off baseline).
    flightrec_enabled: bool = True
    # ring-buffer capacity: retained entries past it evict oldest-first
    flightrec_entries: int = 256
    # floor under the rolling p95 retention threshold, in milliseconds —
    # a uniformly fast call type must not retain its own p95 noise
    flightrec_min_ms: float = 25.0
    # continuous profiling plane (docs/profiling.md): a background
    # sampler over sys._current_frames() aggregates folded stacks into
    # a ring of rotating time segments so GET /debug/profile serves a
    # flame graph of the recent past instantly. Disabling removes the
    # sampler thread entirely (the bench's profiler-off baseline).
    profiler_enabled: bool = True
    # samples per second; the overhead gate (make bench-profile) holds
    # at the default — raise for finer stacks on a box with headroom
    profiler_hz: float = 20.0
    # seconds per ring segment, and retained segments: history depth is
    # segment-s × segments (defaults: 16 minutes)
    profiler_segment_s: float = 60.0
    profiler_segments: int = 16
    # saturation probes (docs/profiling.md): the event-loop lag probe,
    # worker-utilization sampling, and the GIL-contention estimator
    # thread behind GET /debug/saturation. Lock-contention counting is
    # structural (the shim costs one nonblocking attempt) and stays on
    # regardless.
    saturation_probes_enabled: bool = True
    # settle-time router-decision audit (docs/query-routing.md):
    # router_misroute_total / router_estimate_error_ratio and the
    # /debug/vars routerAudit drift section; disable for the bench's
    # instrumented-off baseline
    router_audit_enabled: bool = True
    # workload intelligence plane (docs/workload.md): always-on
    # continuous capture of every settled public query (fingerprint,
    # latency, route, status) feeding the heavy-hitter sketch, the
    # cachability estimate, and GET /debug/workload. Disabling removes
    # the plane from the settle path entirely (the bench's capture-off
    # baseline).
    workload_capture_enabled: bool = True
    # in-memory capture ring capacity (records; oldest evict first)
    workload_capture_entries: int = 4096
    # fraction of settled queries recorded into the ring/spill
    # (deterministic every-Nth sampling; the sketch and SLO engine
    # observe every query regardless)
    workload_sample_rate: float = 1.0
    # heavy-hitter sketch size: distinct fingerprints tracked with full
    # per-fingerprint stats (SpaceSaving top-K)
    workload_top_k: int = 64
    # directory for durable capture spill ("" = in-memory ring only):
    # sampled records accumulate into size/age-bounded JSONL segments
    # replayable by `pilosa_tpu replay`
    workload_capture_path: str = ""
    # spill segment bounds: a segment is cut when its buffered records
    # exceed this many bytes or this age in seconds, whichever first
    # (both evaluated as records arrive — an idle server's buffered
    # tail flushes at shutdown; capture is best-effort by design)
    workload_spill_max_bytes: int = 4_000_000
    workload_spill_max_age_s: float = 60.0
    # spill segments retained on disk (oldest deleted past the cap)
    workload_spill_segments: int = 8
    # mutation-stamped cross-request result cache (docs/result-cache.md):
    # byte budget for retained settled results (0 disables the cache —
    # equivalent to result-cache-mode = "off")
    result_cache_bytes: int = 64_000_000
    # admission threshold: results whose measured execution cost is
    # below this are not cached (the 0.2ms Count is cheaper to recompute
    # than to ledger)
    result_cache_min_cost_ms: float = 1.0
    # "on" serves repeated reads from settled results; "off" makes the
    # cache fully inert (the bench's cache-off baseline)
    result_cache_mode: str = "on"
    # SLO objectives (docs/workload.md grammar), comma/semicolon-
    # separated: "<call>:p95<50ms:99.9" (99.9% of <call> queries settle
    # OK within 50ms) or "<call>:errors:99.9" (availability only);
    # "*" matches any call type. "" disables the SLO engine.
    slo_targets: str = ""
    # structured access log: "json" emits one JSON line per request
    # (method, route, status, latency, bytes, trace id, fingerprint)
    # to the server log sink; "" disables (the default)
    access_log_format: str = ""
    # multi-process serving (docs/multiprocess.md): N > 1 turns
    # `pilosa_tpu server` into a SUPERVISOR that spawns N child server
    # processes sharing the public port via SO_REUSEPORT (accept-and-
    # pass fallback where the option is missing), each child owning a
    # disjoint shard subset through ordinary cluster membership over
    # localhost — the one-process GIL/worker-pool ceiling becomes
    # horizontal headroom. 1 (the default) serves in-process as before.
    serving_processes: int = 1
    # supervisor→child plumbing (the supervisor sets these for its
    # children; operators only need them for hand-built topologies):
    # an EXTRA public host:port this child binds with SO_REUSEPORT once
    # its cluster join completes — readiness gating: the shared port
    # never routes to a child that cannot serve its shard subset yet
    shared_bind: str = ""
    # unix-socket path where an accept-and-pass parent delivers
    # accepted public connections as SCM_RIGHTS fds; the child adopts
    # each into its event loop (the no-SO_REUSEPORT fallback)
    fd_pass_socket: str = ""
    # path of the supervisor's fleet-state JSON (listener mode, child
    # pids, restart counts) — children read it to serve the stitched
    # GET /debug/processes fleet view
    supervisor_state: str = ""
    # restart-on-crash backoff: the first respawn of a crashed child
    # waits base seconds, doubling per consecutive crash up to max
    # (a child that stays up resets the streak)
    supervisor_restart_backoff_s: float = 0.5
    supervisor_restart_backoff_max_s: float = 10.0
    # metrics
    metric_service: str = "prometheus"  # prometheus | statsd | none
    statsd_host: str = ""  # host:port for metric_service = "statsd"
    # TLS (reference: server/config.go tls.certificate / tls.key /
    # tls.skip-verify). Setting certificate+key serves HTTPS; skip_verify
    # disables peer-certificate verification on the internal client (for
    # self-signed deployments, as upstream).
    tls_certificate: str = ""
    tls_key: str = ""
    tls_skip_verify: bool = False

    @property
    def host(self) -> str:
        return self.bind.split(":")[0]

    @property
    def port(self) -> int:
        return int(self.bind.split(":")[1])

    @property
    def scheme(self) -> str:
        return "https" if self.tls_certificate else "http"

    @property
    def uri(self) -> str:
        return f"{self.scheme}://{self.bind}"

    @property
    def node_id(self) -> str:
        return self.name or self.bind


_ENV_PREFIX = "PILOSA_TPU_"


def _coerce(value: str, default):
    """Coerce an env string to the type of the field's default value."""
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(value)
    if isinstance(default, float):
        return float(value)
    if isinstance(default, list):
        return [s for s in value.split(",") if s]
    return value


def load_config(
    path: str | None = None, env: dict | None = None, overrides: dict | None = None
) -> Config:
    """defaults ← TOML file ← env ← explicit overrides (CLI flags)."""
    cfg = Config()
    if path:
        with open(path, "rb") as f:
            data = tomllib.load(f)
        for f_def in fields(Config):
            key = f_def.name.replace("_", "-")
            if key in data:
                setattr(cfg, f_def.name, data[key])
            elif f_def.name in data:
                setattr(cfg, f_def.name, data[f_def.name])
    env = env if env is not None else os.environ
    defaults = Config()
    for f_def in fields(Config):
        env_key = _ENV_PREFIX + f_def.name.upper()
        if env_key in env:
            setattr(
                cfg,
                f_def.name,
                _coerce(env[env_key], getattr(defaults, f_def.name)),
            )
    for k, v in (overrides or {}).items():
        if v is not None:
            setattr(cfg, k, v)
    return cfg


def config_template() -> str:
    """TOML template (reference: `pilosa generate-config`)."""
    return (
        'bind = "127.0.0.1:10101"\n'
        'data-dir = "~/.pilosa_tpu"\n'
        'name = ""\n'
        "coordinator = false\n"
        "seeds = []\n"
        "replica-n = 1\n"
        "anti-entropy-interval = 600.0\n"
        "heartbeat-interval = 2.0\n"
        "diagnostics-interval = 3600.0\n"
        'serving-mode = "event"\n'
        "max-connections = 0\n"
        "admission-queue-depth = 256\n"
        "keepalive-idle-s = 75.0\n"
        "request-read-timeout-s = 10.0\n"
        "http-worker-threads = 0\n"
        "max-writes-per-request = 5000\n"
        "long-query-time = 0.0\n"
        'log-path = ""\n'
        "mesh-enabled = true\n"
        "mesh-words-axis = 1\n"
        "device-init-timeout = 300.0\n"
        "query-gate-wait = 60.0\n"
        'coordinator-address = ""\n'
        "num-processes = 0\n"
        "process-id = -1\n"
        'route-mode = "auto"\n'
        "device-stack-budget-bytes = 0\n"
        "route-crossover-words = 0.0\n"
        "route-dispatch-ms = 1.0\n"
        "route-readback-ms = 2.0\n"
        "route-device-words-per-s = 25e9\n"
        "route-mesh-dispatch-ms = 2.0\n"
        "route-mesh-readback-ms = 2.0\n"
        "device-probe-ttl = 900.0\n"
        'batch-mode = "adaptive"\n'
        "batch-window-us = 250.0\n"
        "batch-max-queries = 64\n"
        "query-timeout-ms = 0.0\n"
        "rpc-retries = 2\n"
        "rpc-backoff-base-ms = 20.0\n"
        "rpc-backoff-cap-ms = 500.0\n"
        "breaker-enabled = true\n"
        "breaker-failure-threshold = 3\n"
        "breaker-cooldown-ms = 5000.0\n"
        'fault-rules = ""\n'
        "fault-seed = 0\n"
        'fs-fault-rules = ""\n'
        "movement-max-concurrent = 4\n"
        "movement-max-mbit = 0.0\n"
        'wal-fsync-mode = "batch"\n'
        "compaction-workers = 1\n"
        "compaction-max-debt = 64\n"
        "holder-load-workers = 8\n"
        "holder-load-min-fragments = 32\n"
        "flightrec-enabled = true\n"
        "flightrec-entries = 256\n"
        "flightrec-min-ms = 25.0\n"
        "profiler-enabled = true\n"
        "profiler-hz = 20.0\n"
        "profiler-segment-s = 60.0\n"
        "profiler-segments = 16\n"
        "saturation-probes-enabled = true\n"
        "router-audit-enabled = true\n"
        "workload-capture-enabled = true\n"
        "workload-capture-entries = 4096\n"
        "workload-sample-rate = 1.0\n"
        "workload-top-k = 64\n"
        'workload-capture-path = ""\n'
        "workload-spill-max-bytes = 4000000\n"
        "workload-spill-max-age-s = 60.0\n"
        "workload-spill-segments = 8\n"
        "result-cache-bytes = 64000000\n"
        "result-cache-min-cost-ms = 1.0\n"
        'result-cache-mode = "on"\n'
        'slo-targets = ""\n'
        'access-log-format = ""\n'
        "serving-processes = 1\n"
        'shared-bind = ""\n'
        'fd-pass-socket = ""\n'
        'supervisor-state = ""\n'
        "supervisor-restart-backoff-s = 0.5\n"
        "supervisor-restart-backoff-max-s = 10.0\n"
        'metric-service = "prometheus"\n'
        'statsd-host = ""\n'
        'tls-certificate = ""\n'
        'tls-key = ""\n'
        "tls-skip-verify = false\n"
    )


def dump_config(cfg: Config) -> str:
    out = []
    for f_def in fields(Config):
        v = getattr(cfg, f_def.name)
        key = f_def.name.replace("_", "-")
        if isinstance(v, str):
            out.append(f'{key} = "{v}"')
        elif isinstance(v, bool):
            out.append(f"{key} = {str(v).lower()}")
        elif isinstance(v, list):
            out.append(f"{key} = {v!r}")
        else:
            out.append(f"{key} = {v}")
    return "\n".join(out) + "\n"
