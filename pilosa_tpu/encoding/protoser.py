"""Protobuf serializer: framework result/request dicts ↔ wire messages.

Reference: encoding/proto/proto.go (Serializer — Marshal/Unmarshal of
QueryRequest/QueryResponse/Import* payloads). The framework's canonical
in-process result shapes are the JSON-able dicts produced by
``api.query`` (see server/api.py _result_json); this module maps those
to/from the ``pilosa.proto`` messages so HTTP clients can content-
negotiate ``application/x-protobuf`` exactly like the reference's
handler does.
"""

from __future__ import annotations

from typing import Any

from pilosa_tpu.encoding import pilosa_pb2 as pb

CONTENT_TYPE = "application/x-protobuf"

# QueryResult.type tags (reference: QueryResult.Type codes)
T_NIL = 0
T_ROW = 1
T_COUNT = 2
T_PAIRS = 3
T_VAL_COUNT = 4
T_CHANGED = 5
T_ROW_IDS = 6
T_GROUP_COUNTS = 7

_ATTR_STRING = 1
_ATTR_INT = 2
_ATTR_BOOL = 3
_ATTR_FLOAT = 4


def attrs_to_proto(attrs: dict[str, Any]) -> list[pb.Attr]:
    out = []
    for k in sorted(attrs):
        v = attrs[k]
        a = pb.Attr(key=k)
        if isinstance(v, bool):
            a.type = _ATTR_BOOL
            a.bool_value = v
        elif isinstance(v, int):
            a.type = _ATTR_INT
            a.int_value = v
        elif isinstance(v, float):
            a.type = _ATTR_FLOAT
            a.float_value = v
        else:
            a.type = _ATTR_STRING
            a.string_value = str(v)
        out.append(a)
    return out


def attrs_from_proto(attrs) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for a in attrs:
        if a.type == _ATTR_BOOL:
            out[a.key] = a.bool_value
        elif a.type == _ATTR_INT:
            out[a.key] = a.int_value
        elif a.type == _ATTR_FLOAT:
            out[a.key] = a.float_value
        else:
            out[a.key] = a.string_value
    return out


# ---------------------------------------------------------------- results
def result_to_proto(r: Any) -> pb.QueryResult:
    """One result entry (a ``_result_json`` value) → QueryResult."""
    q = pb.QueryResult()
    if r is None:
        q.type = T_NIL
        return q
    if isinstance(r, bool):
        q.type = T_CHANGED
        q.changed = r
        return q
    if isinstance(r, int):
        q.type = T_COUNT
        q.n = r
        return q
    if isinstance(r, dict):
        if "columns" in r or ("keys" in r and "rows" not in r):
            q.type = T_ROW
            q.row.columns.extend(r.get("columns", []))
            q.row.keys.extend(r.get("keys", []))
            q.row.keyed = "keys" in r
            q.row.attrs.extend(attrs_to_proto(r.get("attrs", {})))
            return q
        if "value" in r and "count" in r:
            q.type = T_VAL_COUNT
            q.val_count.val = r["value"]
            q.val_count.count = r["count"]
            return q
        if "rows" in r:
            q.type = T_ROW_IDS
            q.row_identifiers.rows.extend(r["rows"])
            q.row_identifiers.keys.extend(r.get("keys", []))
            q.row_identifiers.keyed = "keys" in r
            return q
    if isinstance(r, list):
        if r and isinstance(r[0], dict) and "group" in r[0]:
            q.type = T_GROUP_COUNTS
            for g in r:
                gc = q.group_counts.add()
                gc.count = g["count"]
                if "sum" in g:
                    gc.sum = g["sum"]
                    gc.has_sum = True
                for e in g["group"]:
                    fr = gc.group.add()
                    fr.field = e["field"]
                    fr.row_id = e.get("rowID", 0)
                    if e.get("rowKey"):
                        fr.row_key = e["rowKey"]
            return q
        q.type = T_PAIRS
        for p in r:
            q.pairs.add(
                id=p.get("id", 0), key=p.get("key", ""), count=p["count"]
            )
        return q
    raise TypeError(f"cannot serialize result {r!r}")


def result_from_proto(q: pb.QueryResult) -> Any:
    if q.type == T_NIL:
        return None
    if q.type == T_CHANGED:
        return q.changed
    if q.type == T_COUNT:
        return q.n
    if q.type == T_ROW:
        out: dict[str, Any] = {}
        if q.row.keyed:
            out["keys"] = list(q.row.keys)
        else:
            out["columns"] = list(q.row.columns)
        if q.row.attrs:
            out["attrs"] = attrs_from_proto(q.row.attrs)
        return out
    if q.type == T_VAL_COUNT:
        return {"value": q.val_count.val, "count": q.val_count.count}
    if q.type == T_ROW_IDS:
        out = {"rows": list(q.row_identifiers.rows)}
        if q.row_identifiers.keyed:
            out["keys"] = list(q.row_identifiers.keys)
        return out
    if q.type == T_GROUP_COUNTS:
        groups = []
        for gc in q.group_counts:
            g: dict[str, Any] = {
                "group": [
                    {
                        "field": fr.field,
                        "rowID": fr.row_id,
                        **({"rowKey": fr.row_key} if fr.row_key else {}),
                    }
                    for fr in gc.group
                ],
                "count": gc.count,
            }
            if gc.has_sum:
                g["sum"] = gc.sum
            groups.append(g)
        return groups
    if q.type == T_PAIRS:
        return [
            {
                "id": p.id,
                **({"key": p.key} if p.key else {}),
                "count": p.count,
            }
            for p in q.pairs
        ]
    raise TypeError(f"unknown QueryResult type {q.type}")


def response_to_bytes(resp: dict) -> bytes:
    """api.query response dict → serialized QueryResponse."""
    m = pb.QueryResponse()
    if resp.get("error"):
        m.err = resp["error"]
    for r in resp.get("results", []):
        m.results.append(result_to_proto(r))
    for cas in resp.get("columnAttrs", []):
        c = m.column_attr_sets.add()
        c.id = cas.get("id", 0)
        if cas.get("key"):
            c.key = cas["key"]
        c.attrs.extend(attrs_to_proto(cas.get("attrs", {})))
    return m.SerializeToString()


def response_from_bytes(data: bytes) -> dict:
    m = pb.QueryResponse()
    m.ParseFromString(data)
    out: dict[str, Any] = {"results": [result_from_proto(r) for r in m.results]}
    if m.err:
        out["error"] = m.err
    if m.column_attr_sets:
        out["columnAttrs"] = [
            {
                "id": c.id,
                **({"key": c.key} if c.key else {}),
                "attrs": attrs_from_proto(c.attrs),
            }
            for c in m.column_attr_sets
        ]
    return out


def import_response_to_bytes(err: str = "") -> bytes:
    return pb.ImportResponse(err=err).SerializeToString()


def import_response_from_bytes(data: bytes) -> str:
    m = pb.ImportResponse()
    m.ParseFromString(data)
    return m.err


# ---------------------------------------------------------------- requests
def query_request_to_bytes(
    query: str, shards: list[int] | None = None, **opts
) -> bytes:
    m = pb.QueryRequest(query=query)
    if shards:
        m.shards.extend(shards)
    m.column_attrs = bool(opts.get("column_attrs"))
    m.remote = bool(opts.get("remote"))
    m.exclude_row_attrs = bool(opts.get("exclude_row_attrs"))
    m.exclude_columns = bool(opts.get("exclude_columns"))
    return m.SerializeToString()


def query_request_from_bytes(data: bytes) -> tuple[str, list[int] | None]:
    m = pb.QueryRequest()
    m.ParseFromString(data)
    return m.query, list(m.shards) or None


def import_request_to_bytes(payload: dict) -> bytes:
    m = pb.ImportRequest()
    m.index = payload.get("index", "")
    m.field = payload.get("field", "")
    m.shard = payload.get("shard", 0)
    m.row_ids.extend(payload.get("rowIDs", []))
    m.row_keys.extend(payload.get("rowKeys", []))
    m.column_ids.extend(payload.get("columnIDs", []))
    m.column_keys.extend(payload.get("columnKeys", []))
    m.timestamps.extend(int(t) for t in payload.get("timestamps", []))
    m.clear = bool(payload.get("clear"))
    return m.SerializeToString()


def import_request_from_bytes(data: bytes) -> dict:
    m = pb.ImportRequest()
    m.ParseFromString(data)
    out: dict[str, Any] = {}
    if m.row_ids:
        out["rowIDs"] = list(m.row_ids)
    if m.row_keys:
        out["rowKeys"] = list(m.row_keys)
    if m.column_ids:
        out["columnIDs"] = list(m.column_ids)
    if m.column_keys:
        out["columnKeys"] = list(m.column_keys)
    if m.timestamps:
        out["timestamps"] = list(m.timestamps)
    if m.clear:
        out["clear"] = True
    return out


def translate_keys_request_to_bytes(
    index: str, keys: list[str], field: str = "", create: bool = True
) -> bytes:
    return pb.TranslateKeysRequest(
        index=index, field=field, keys=keys, lookup_only=not create
    ).SerializeToString()


def translate_keys_request_from_bytes(data: bytes) -> dict:
    m = pb.TranslateKeysRequest()
    m.ParseFromString(data)
    return {
        "index": m.index,
        "field": m.field,
        "keys": list(m.keys),
        "create": not m.lookup_only,
    }


def translate_keys_response_to_bytes(ids: list[int | None]) -> bytes:
    """None (key not found on a lookup-only request) maps to 0 — IDs
    start at 1, so 0 is unambiguous."""
    return pb.TranslateKeysResponse(
        ids=[i or 0 for i in ids]
    ).SerializeToString()


def translate_keys_response_from_bytes(data: bytes) -> list[int]:
    m = pb.TranslateKeysResponse()
    m.ParseFromString(data)
    return list(m.ids)


def import_value_request_to_bytes(payload: dict) -> bytes:
    m = pb.ImportValueRequest()
    m.index = payload.get("index", "")
    m.field = payload.get("field", "")
    m.shard = payload.get("shard", 0)
    m.column_ids.extend(payload.get("columnIDs", []))
    m.column_keys.extend(payload.get("columnKeys", []))
    m.values.extend(payload.get("values", []))
    m.clear = bool(payload.get("clear"))
    return m.SerializeToString()


def import_roaring_request_to_bytes(data: bytes, view: str = "standard") -> bytes:
    return pb.ImportRoaringRequest(view=view, data=data).SerializeToString()


def import_roaring_request_from_bytes(body: bytes) -> tuple[bytes, str]:
    """Returns (data, view); view is "" when the envelope left it unset
    so the caller can fall back to the ?view= query parameter."""
    m = pb.ImportRoaringRequest()
    m.ParseFromString(body)
    return m.data, m.view


def import_value_request_from_bytes(data: bytes) -> dict:
    m = pb.ImportValueRequest()
    m.ParseFromString(data)
    out: dict[str, Any] = {}
    if m.column_ids:
        out["columnIDs"] = list(m.column_ids)
    if m.column_keys:
        out["columnKeys"] = list(m.column_keys)
    if m.values:
        out["values"] = list(m.values)
    if m.clear:
        out["clear"] = True
    return out
