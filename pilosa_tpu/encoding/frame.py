"""Framed binary envelope for the internal node↔node data plane.

Reference: internal/internal.proto + http/client.go (InternalClient) move
node↔node payloads as protobuf. This framework keeps JSON for CONTROL
(readable, schema-free) but moves the FAT arrays — query-result bitmap
segments, import column/row id vectors, anti-entropy block pairs — as raw
little-endian binary blobs referenced from the control header, so
multi-GB internal transfers pay zero base64 inflation and no
per-element JSON parse.

Layout (all little-endian):

    magic  b"PTF1"
    u32    header_len          (JSON control bytes)
    u32    n_blobs
    u64[n] blob lengths
    bytes  header JSON
    bytes  blob 0 | blob 1 | …

Control JSON references blobs by index (position in the blob table).
Receivers sniff the magic, so every framed route also accepts plain
JSON from external tools. Like the reference's protobuf internal plane,
SENDERS frame unconditionally: the node↔node wire assumes a
uniform-version cluster (mixed-version rolling upgrades are out of
scope, as they were for the JSON wire this replaces).
"""

from __future__ import annotations

import json
import struct

import numpy as np

MAGIC = b"PTF1"
CONTENT_TYPE = "application/x-pilosa-frame"


def is_frame(data: bytes) -> bool:
    return len(data) >= 4 and bytes(data[:4]) == MAGIC


def encode_frame(control: dict, blobs: list[bytes]) -> bytes:
    header = json.dumps(control).encode()
    parts = [
        MAGIC,
        struct.pack("<II", len(header), len(blobs)),
        struct.pack(f"<{len(blobs)}Q", *[len(b) for b in blobs]),
        header,
    ]
    parts.extend(bytes(b) for b in blobs)
    return b"".join(parts)


def decode_frame(data: bytes) -> tuple[dict, list[memoryview]]:
    mv = memoryview(data)
    if bytes(mv[:4]) != MAGIC:
        raise ValueError("not a pilosa frame")
    if len(mv) < 12:
        raise ValueError("truncated frame header")
    header_len, n_blobs = struct.unpack_from("<II", mv, 4)
    if len(mv) < 12 + 8 * n_blobs:
        raise ValueError("truncated frame blob table")
    lens = struct.unpack_from(f"<{n_blobs}Q", mv, 12)
    off = 12 + 8 * n_blobs
    # exact-length check: a truncated body must fail loudly, not yield
    # silently short blobs (an 8-byte-aligned shortfall would otherwise
    # decode to HALF the column ids with no error)
    if off + header_len + sum(lens) != len(mv):
        raise ValueError(
            f"frame length mismatch: declared "
            f"{off + header_len + sum(lens)}, got {len(mv)}"
        )
    control = json.loads(bytes(mv[off : off + header_len]))
    off += header_len
    blobs: list[memoryview] = []
    for length in lens:
        blobs.append(mv[off : off + length])
        off += length
    return control, blobs


def pack_u64(values) -> bytes:
    return np.asarray(values, dtype=np.uint64).tobytes()


def unpack_u64(blob) -> np.ndarray:
    # copy: frombuffer over a memoryview yields a read-only view into the
    # request buffer; downstream (fragment import, reduce) assumes owned,
    # writable arrays
    return np.frombuffer(blob, dtype=np.uint64).copy()


def pack_u32(values) -> bytes:
    return np.asarray(values, dtype=np.uint32).tobytes()


def unpack_u32(blob) -> np.ndarray:
    return np.frombuffer(blob, dtype=np.uint32).copy()
