"""Wire serialization (reference: internal/internal.proto +
encoding/proto/proto.go).

``AVAILABLE`` is False when the google.protobuf runtime is missing; the
HTTP layer then serves JSON only (the reference requires protobuf
unconditionally; here it is an optional content type).
"""

from __future__ import annotations

try:
    from google.protobuf.message import DecodeError  # noqa: F401

    from pilosa_tpu.encoding import protoser  # noqa: F401
    from pilosa_tpu.encoding.protoser import CONTENT_TYPE  # noqa: F401

    AVAILABLE = True
except ImportError:  # pragma: no cover - protobuf is baked into the image
    protoser = None  # type: ignore[assignment]
    CONTENT_TYPE = "application/x-protobuf"
    AVAILABLE = False

    class DecodeError(Exception):  # type: ignore[no-redef]
        pass
