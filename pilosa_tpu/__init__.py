"""pilosa_tpu — a TPU-native distributed bitmap-index framework.

A ground-up re-design of the capabilities of Pilosa (reference:
``princessd8251/pilosa``, a fork of the Go ``pilosa/pilosa`` distributed
roaring-bitmap index) for TPU hardware:

- fragments are dense packed bit-matrices (``uint32[rows, ShardWidth/32]``)
  laid out across a ``jax.sharding.Mesh`` instead of per-node Go roaring heaps;
- container set-ops / popcounts lower to XLA/Pallas bitwise kernels instead of
  the reference's CPU hot loops (reference: roaring/roaring.go);
- cross-shard aggregation is a ``psum`` over ICI inside one jitted program
  instead of HTTP scatter-gather (reference: executor.go mapReduce);
- roaring remains the at-rest / interchange format, implemented host-side
  (numpy + optional C++ accelerator).

Layer map mirrors SURVEY.md §2:
    roaring/   L0 bitmap engine (host codec + oracle)
    core/      L1 storage & data model (Holder/Index/Field/View/Fragment)
    pql/       L2 query language (parser → AST)
    executor/  L2 query execution (AST → jitted device programs)
    parallel/  L3 mesh/topology (device mesh + cluster partitioning)
    server/    L5/L6 API façade, HTTP transport, server runtime
    ops/       TPU kernel library (the "native" hot loops)
    utils/     X1 cross-cutting (stats, tracing, config, logging)
"""

from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXP, WORDS_PER_SHARD

__version__ = "0.1.0"

__all__ = [
    "SHARD_WIDTH",
    "SHARD_WIDTH_EXP",
    "WORDS_PER_SHARD",
    "__version__",
]
