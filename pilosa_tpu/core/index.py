"""Index — a namespace of fields over one column universe.

Reference: index.go (Index, CreateField, DeleteField; options keys /
trackExistence). When ``track_existence`` is on, every column write also
sets row 0 of the internal ``_exists`` field, which backs Not() and All().
"""

from __future__ import annotations

import json
import os
import threading
import shutil
from dataclasses import asdict, dataclass

import numpy as np

from pilosa_tpu.core.attrstore import AttrStore
from pilosa_tpu.core.field import FIELD_SET, VIEW_STANDARD, Field, FieldOptions
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils import durable

EXISTENCE_FIELD = "_exists"


@dataclass
class IndexOptions:
    keys: bool = False
    track_existence: bool = True


class Index:
    def __init__(self, name: str, path: str | None, options: IndexOptions | None = None):
        self.name = name
        self.path = path  # <holder-path>/<index-name>
        self.options = options or IndexOptions()
        self.fields: dict[str, Field] = {}
        self._create_lock = threading.Lock()
        # background compaction queue, inherited by fields created here
        self.compactor = None
        # column attributes (reference: index.go columnAttrStore) and
        # column-key translation (reference: translate.go)
        self.column_attrs = AttrStore(
            os.path.join(path, ".column_attrs.json") if path else None
        )
        self.column_attrs.open()
        self.column_keys = TranslateStore(
            os.path.join(path, ".keys.jsonl") if path else None
        )
        self.column_keys.open()

    # -------------------------------------------------------------- meta
    def save_meta(self) -> None:
        if self.path is None:
            return
        os.makedirs(self.path, exist_ok=True)
        durable.atomic_write_file(
            os.path.join(self.path, ".meta.json"),
            json.dumps({"options": asdict(self.options)}),
        )

    @classmethod
    def load(
        cls, name: str, path: str, compactor=None, pool=None
    ) -> "Index":
        with open(os.path.join(path, ".meta.json")) as f:
            meta = json.load(f)
        idx = cls(name, path, IndexOptions(**meta["options"]))
        idx.compactor = compactor
        for entry in sorted(os.listdir(path)):
            field_path = os.path.join(path, entry)
            if os.path.isdir(field_path) and os.path.exists(
                os.path.join(field_path, ".meta.json")
            ):
                idx.fields[entry] = Field.load(
                    name, entry, field_path, compactor=compactor, pool=pool
                )
        return idx

    # ------------------------------------------------------------ fields
    def field(self, name: str) -> Field | None:
        return self.fields.get(name)

    def create_field(self, name: str, options: FieldOptions | None = None) -> Field:
        if name in self.fields:
            raise ValueError(f"field {name!r} already exists")
        return self.create_field_if_not_exists(name, options)

    def create_field_if_not_exists(
        self, name: str, options: FieldOptions | None = None
    ) -> Field:
        existing = self.fields.get(name)
        if existing is not None:
            return existing
        with self._create_lock:
            return self._create_field_locked(name, options)

    def _create_field_locked(
        self, name: str, options: FieldOptions | None = None
    ) -> Field:
        existing = self.fields.get(name)
        if existing is not None:
            return existing
        field_path = os.path.join(self.path, name) if self.path else None
        f = Field(self.name, name, field_path, options or FieldOptions())
        f.compactor = self.compactor
        f.save_meta()
        self.fields[name] = f
        return f

    def delete_field(self, name: str) -> None:
        f = self.fields.pop(name, None)
        if f is None:
            raise KeyError(f"field {name!r} not found")
        f.close()
        if f.path and os.path.isdir(f.path):
            shutil.rmtree(f.path)

    # --------------------------------------------------------- existence
    def existence_field(self) -> Field | None:
        if not self.options.track_existence:
            return None
        return self.create_field_if_not_exists(
            EXISTENCE_FIELD, FieldOptions(field_type=FIELD_SET, cache_type="none")
        )

    def mark_columns_exist(self, cols: np.ndarray) -> None:
        ef = self.existence_field()
        if ef is None or not np.asarray(cols).size:
            return
        cols = np.asarray(cols, dtype=np.uint64)
        from pilosa_tpu.core.fragment import MAX_OP_N

        if cols.size <= MAX_OP_N:  # the fragment's own snapshot threshold
            # small delta: the bit-list path op-logs it (cheap, durable)
            ef.import_bulk(np.zeros(cols.size, dtype=np.uint64), cols)
            return
        # bulk delta (import-roaring scale): a per-shard roaring union
        # with one snapshot — the bit-list machinery (sort, group,
        # op-log append, snapshot anyway at this size) is pure overhead
        view = ef.create_view_if_not_exists(VIEW_STANDARD)
        shards = cols // np.uint64(SHARD_WIDTH)
        for sh in np.unique(shards).tolist():
            frag = view.create_fragment_if_not_exists(int(sh))
            # existence row is 0: position == in-shard column offset
            frag.union_positions(cols[shards == sh] % np.uint64(SHARD_WIDTH))

    def mark_shard_columns(self, shard: int, col_bitmap) -> None:
        """Existence marking for a single-shard bulk adopt: the caller
        already holds the delta's shard-relative column set as a Bitmap
        (folded container-wise off the adopt delta — see
        roaring/build.py:fold_to_columns), so this unions it straight
        into the existence fragment with one WAL append. Row 0 of
        ``_exists`` puts position == column offset, so the folded bitmap
        IS the position bitmap."""
        ef = self.existence_field()
        if ef is None or not col_bitmap._containers:
            return
        frag = ef.create_view_if_not_exists(
            VIEW_STANDARD
        ).create_fragment_if_not_exists(int(shard))
        with frag._lock:
            if frag.row_count(0) >= SHARD_WIDTH:
                # every column of the shard is already marked: the union
                # is a no-op and must not pay an O(delta) merge + WAL
                # frame per post — sustained re-ingest into a warm shard
                # hits this on every import
                return
            frag.union_bitmap(col_bitmap)

    def available_shards(self) -> set[int]:
        shards: set[int] = set()
        for f in self.fields.values():
            shards |= f.available_shards()
        return shards

    def close(self) -> None:
        for f in self.fields.values():
            f.close()
        self.column_attrs.close()
        self.column_keys.close()
