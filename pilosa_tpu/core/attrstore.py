"""Row/column attribute store.

Reference: attrstore.go + boltdb/attrstore.go (AttrStore; attrs synced via
100-ID block checksums). BoltDB is replaced by a JSON file persisted on
mutation; the block-checksum diff surface is kept for anti-entropy.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

ATTR_BLOCK_SIZE = 100


class AttrStore:
    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        self._attrs: dict[int, dict] = {}

    def open(self) -> None:
        with self._lock:
            if self.path and os.path.exists(self.path):
                with open(self.path) as f:
                    raw = json.load(f)
                self._attrs = {int(k): v for k, v in raw.items()}

    def close(self) -> None:
        pass

    def _persist(self) -> None:
        if self.path is None:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self._attrs.items()}, f)
        os.replace(tmp, self.path)

    def set_attrs(self, id_: int, attrs: dict) -> None:
        """Merge attrs for an ID; null values delete keys (reference:
        AttrStore.SetAttrs)."""
        with self._lock:
            current = self._attrs.setdefault(id_, {})
            for k, v in attrs.items():
                if v is None:
                    current.pop(k, None)
                else:
                    current[k] = v
            if not current:
                self._attrs.pop(id_, None)
            self._persist()

    def attrs(self, id_: int) -> dict:
        with self._lock:
            return dict(self._attrs.get(id_, {}))

    def block_checksums(self) -> list[tuple[int, bytes]]:
        with self._lock:
            blocks: dict[int, list[int]] = {}
            for id_ in self._attrs:
                blocks.setdefault(id_ // ATTR_BLOCK_SIZE, []).append(id_)
            out = []
            for block_id in sorted(blocks):
                h = hashlib.blake2b(digest_size=16)
                for id_ in sorted(blocks[block_id]):
                    h.update(
                        json.dumps(
                            [id_, self._attrs[id_]], sort_keys=True
                        ).encode()
                    )
                out.append((block_id, h.digest()))
            return out

    def block_data(self, block_id: int) -> dict[int, dict]:
        with self._lock:
            lo = block_id * ATTR_BLOCK_SIZE
            hi = lo + ATTR_BLOCK_SIZE
            return {i: dict(a) for i, a in self._attrs.items() if lo <= i < hi}

    def merge_block(self, data: dict[int, dict]) -> None:
        with self._lock:
            for id_, attrs in data.items():
                current = self._attrs.setdefault(int(id_), {})
                current.update(attrs)
            self._persist()
