"""Row/column attribute store.

Reference: attrstore.go + boltdb/attrstore.go (AttrStore; attrs synced via
100-ID block checksums). BoltDB is replaced by a JSON file persisted on
mutation; the block-checksum diff surface is kept for anti-entropy.

Divergence from the reference, deliberate: every attribute key carries a
last-writer-wins timestamp, and deletions are kept as tombstones. The
reference's block merge is a plain union, which silently resurrects
deleted attrs when a node that missed the delete broadcast rejoins; with
LWW metadata the anti-entropy merge converges on the newest write
(including deletes) instead.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from pilosa_tpu.utils import durable

ATTR_BLOCK_SIZE = 100

# journal entries that trigger a compaction (snapshot rewrite + truncate)
MAX_JOURNAL_OPS = 1024

# tombstones older than this are pruned; must exceed the longest node
# outage you expect anti-entropy to repair, or a delete can resurrect
TOMBSTONE_TTL_SECONDS = 7 * 24 * 3600.0

# value sentinel for a deleted key inside the versioned cell
_TOMBSTONE = None


class AttrStore:
    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        # id → key → [value-or-None(tombstone), lww-timestamp]
        self._cells: dict[int, dict[str, list]] = {}
        self._journal_ops = 0

    def open(self) -> None:
        with self._lock:
            if self.path and os.path.exists(self.path):
                with open(self.path) as f:
                    raw = json.load(f)
                if raw.get("_v") == 2:
                    self._cells = {
                        int(k): {a: list(cell) for a, cell in v.items()}
                        for k, v in raw["cells"].items()
                    }
                else:  # v1 format: plain id → attrs dict, no versions.
                    # Stamp ts=0 so any real (timestamped) write or delete
                    # elsewhere in the cluster wins over migrated data.
                    self._cells = {
                        int(k): {a: [val, 0.0] for a, val in v.items()}
                        for k, v in raw.items()
                        if not k.startswith("_")
                    }
            jp = self._journal_path()
            if jp and os.path.exists(jp):
                with open(jp, "rb") as f:
                    data = f.read()
                good = 0  # bytes of fully replayed records
                for raw in data.splitlines(keepends=True):
                    if not raw.endswith(b"\n"):
                        break  # torn tail from a crash mid-append
                    line = raw.strip()
                    if line:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            break
                        self._apply_cells(rec)
                        self._journal_ops += 1
                    good += len(raw)
                if good < len(data):
                    # truncate the torn tail NOW — appending after it
                    # would weld the next record onto the partial line,
                    # silently discarding everything from the tear on at
                    # the following open
                    durable.truncate_file(jp, good)

    def close(self) -> None:
        pass

    def _journal_path(self) -> str | None:
        return self.path + ".log" if self.path else None

    def _apply_cells(self, rec: dict) -> None:
        """LWW-apply a {id: {key: [value, ts]}} delta (journal replay —
        idempotent, so a crash between compaction's snapshot replace and
        journal truncate just re-applies over the new snapshot)."""
        for id_s, cells in rec.items():
            mine = self._cells.setdefault(int(id_s), {})
            for k, cell in cells.items():
                if k not in mine or mine[k][1] <= cell[1]:
                    mine[k] = [cell[0], cell[1]]

    def _journal(self, delta: dict) -> None:
        """Append one applied delta; O(delta) bytes per write instead of
        the old O(store) full-file rewrite (VERDICT r3 weak #5 — the
        fragment snapshot + ops-log discipline, reused). Compaction folds
        the journal into the snapshot every MAX_JOURNAL_OPS appends."""
        jp = self._journal_path()
        if jp is None or not delta:
            return
        self._journal_ops += 1
        if self._journal_ops > MAX_JOURNAL_OPS:
            self._compact()
            return
        os.makedirs(os.path.dirname(jp), exist_ok=True)
        # WAL-mode append (docs/durability.md): fsynced inline in
        # `always`, group-fsynced at the API's ack barrier in `batch`
        durable.append_wal(jp, (json.dumps(delta) + "\n").encode())

    def _compact(self) -> None:
        self._prune_tombstones()
        self._persist()
        jp = self._journal_path()
        if jp and os.path.exists(jp):
            # reset AFTER the snapshot replace is durable: a crash
            # between the two just replays the journal over the new
            # snapshot (idempotent LWW apply)
            durable.truncate_file(jp, 0)
        self._journal_ops = 0

    def _persist(self) -> None:
        if self.path is None:
            return
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        durable.atomic_write_file(
            self.path,
            json.dumps(
                {
                    "_v": 2,
                    "cells": {str(k): v for k, v in self._cells.items()},
                }
            ),
        )

    def set_attrs(self, id_: int, attrs: dict, ts: float | None = None) -> None:
        """Merge attrs for an ID; null values delete keys — kept as
        tombstones so the delete wins anti-entropy merges (reference:
        AttrStore.SetAttrs). ``ts`` lets a cluster coordinator stamp one
        timestamp on every replica of a broadcast write so LWW never
        compares unsynchronized node clocks."""
        with self._lock:
            now = time.time() if ts is None else ts
            cells = self._cells.setdefault(id_, {})
            applied: dict[str, list] = {}
            for k, v in attrs.items():
                # same newer-ts-wins rule as merge_block: a delayed
                # out-of-order broadcast must not regress a newer write
                if k in cells and cells[k][1] > now:
                    continue
                cell = [_TOMBSTONE if v is None else v, now]
                cells[k] = cell
                applied[k] = cell
            if applied:
                self._journal({str(id_): applied})

    def _prune_tombstones(self) -> None:
        """Drop tombstones past TTL (and then-empty IDs) so churny
        delete workloads don't grow the store without bound."""
        # wall clock on purpose: tombstone timestamps are persisted and
        # replicated — node-local monotonic time means nothing to peers
        horizon = time.time() - TOMBSTONE_TTL_SECONDS  # pilosa: allow(wall-clock)
        for id_ in list(self._cells):
            cells = self._cells[id_]
            for k in [
                k
                for k, c in cells.items()
                if c[0] is _TOMBSTONE and c[1] < horizon
            ]:
                del cells[k]
            if not cells:
                del self._cells[id_]

    def attrs(self, id_: int) -> dict:
        with self._lock:
            return {
                k: cell[0]
                for k, cell in self._cells.get(id_, {}).items()
                if cell[0] is not _TOMBSTONE
            }

    def block_checksums(self) -> list[tuple[int, bytes]]:
        """Checksums cover the versioned cells (tombstones included) so
        two stores agree exactly when their merge states agree."""
        with self._lock:
            blocks: dict[int, list[int]] = {}
            for id_ in self._cells:
                blocks.setdefault(id_ // ATTR_BLOCK_SIZE, []).append(id_)
            out = []
            for block_id in sorted(blocks):
                h = hashlib.blake2b(digest_size=16)
                for id_ in sorted(blocks[block_id]):
                    h.update(
                        json.dumps([id_, self._cells[id_]], sort_keys=True).encode()
                    )
                out.append((block_id, h.digest()))
            return out

    def block_data(self, block_id: int) -> dict[int, dict]:
        """id → {key: [value, ts]} for one block, tombstones included."""
        with self._lock:
            lo = block_id * ATTR_BLOCK_SIZE
            hi = lo + ATTR_BLOCK_SIZE
            return {
                i: {k: list(c) for k, c in cells.items()}
                for i, cells in self._cells.items()
                if lo <= i < hi
            }

    def merge_block(self, data: dict[int, dict]) -> None:
        """Key-wise LWW merge of a peer's block (anti-entropy repair):
        the newer timestamp wins, so missed deletes propagate instead of
        being resurrected."""
        with self._lock:
            applied: dict[str, dict[str, list]] = {}
            for id_, cells in data.items():
                mine = self._cells.setdefault(int(id_), {})
                for k, cell in cells.items():
                    value, ts = cell[0], cell[1]
                    if k not in mine:
                        mine[k] = [value, ts]
                        applied.setdefault(str(id_), {})[k] = mine[k]
                        continue
                    # newer ts wins; equal ts (e.g. two divergent
                    # v1-migrated files, both stamped 0.0) tie-breaks on
                    # the serialized value so every replica converges to
                    # the same winner regardless of merge order
                    my_val, my_ts = mine[k][0], mine[k][1]
                    if my_ts < ts or (
                        my_ts == ts
                        and json.dumps(value, sort_keys=True)
                        > json.dumps(my_val, sort_keys=True)
                    ):
                        mine[k] = [value, ts]
                        applied.setdefault(str(id_), {})[k] = mine[k]
            self._journal(applied)
