"""Fragment — the storage/compute unit: one (index, field, view, shard).

Reference: fragment.go (fragment, setBit/clearBit, row, rows, top,
importRoaring, bulkImport, snapshot, blocks/blockData/checksum). Redesigned
for TPU execution:

- the authoritative store is a host roaring Bitmap (bit position =
  row * SHARD_WIDTH + column-in-shard, identical to the reference) with the
  snapshot + append-only-ops-log durability discipline;
- the *compute* representation is a dense packed bit matrix
  ``uint32[padded_rows, WORDS_PER_SHARD]`` cached on device. Mutations mark
  rows dirty; the next query repacks dirty rows host-side and re-uploads.
  Row capacity grows by doubling so device shapes stay stable and XLA
  recompiles are rare (SURVEY.md §7 hard part (d)).

Rank-cache policy (VERDICT r1 item 7): the reference maintains a per-
fragment row→count rank cache on every mutation because its TopN phase 1
reads it (cache.go rankCache, fragment.go top). Here TopN is EXACT in one
fused device pass over the whole row matrix, so cache maintenance would be
pure write amplification — fragments therefore do NOT update ``cache`` on
mutation. The cache object remains for API parity (``cacheType``/
``cacheSize`` field options round-trip) and is populated only if a caller
explicitly asks via ``rebuild_cache()``.

Unlike the reference there is no per-fragment RWMutex — the executor runs
queries against immutable device arrays, and host mutation is serialized by
a per-fragment lock only around bitmap/ops-log updates.
"""

from __future__ import annotations

import hashlib
import itertools
import os

import numpy as np

_FRAGMENT_UIDS = itertools.count(1)

from pilosa_tpu import roaring
from pilosa_tpu.core.cache import NopCache, make_cache
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.utils import durable, sanitize, saturation
from pilosa_tpu.utils.log import Logger

_LOG = Logger()  # stderr sink; recovery events must be loud

# ops-log length that triggers a snapshot fold (reference default 2000);
# env-overridable so benches/chaos runs can keep the background
# compactor hot without minutes of ingest per fold
MAX_OP_N = int(os.environ.get("PILOSA_TPU_MAX_OP_N", "2000"))
# ops-log BYTE debt that triggers a fold regardless of op count: bulk-
# ingest union records carry whole roaring frames, so a log can grow
# replay-expensive long before op_n trips the count threshold
MAX_OP_BYTES = int(os.environ.get("PILOSA_TPU_MAX_OP_BYTES", str(8 << 20)))
ROWS_PER_BLOCK = 100  # anti-entropy block granularity (reference: HashBlockSize)
MIN_PADDED_ROWS = 8  # sublane tile for int32


def _pad_rows(n: int) -> int:
    p = MIN_PADDED_ROWS
    while p < n:
        p *= 2
    return p


class Fragment:
    def __init__(
        self,
        path: str | None,
        index: str,
        field: str,
        view: str,
        shard: int,
        cache_type: str = "ranked",
        cache_size: int = 50_000,
    ):
        self.path = path
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.bitmap = roaring.Bitmap()
        self.cache = make_cache(cache_type, cache_size)
        self.op_n = 0
        # bytes of framed ops pending in the on-disk log beyond the
        # snapshot — the per-fragment WAL debt the /debug/resources
        # ledger aggregates (replay time after a crash grows with it)
        self.ops_bytes = 0
        self.max_op_n = MAX_OP_N
        self.max_op_bytes = MAX_OP_BYTES
        # serialized size of the last written snapshot: the byte-debt
        # fold trigger scales with it (fold when the log outgrows the
        # snapshot) so sustained bulk ingest pays O(1) amortized write
        # amplification — a FIXED byte trigger re-serializes an ever-
        # growing fragment at an ever-shorter interval
        self.snapshot_bytes = 0
        # contention-counted (docs/profiling.md): every fragment's lock
        # folds into the "fragment" family in /debug/saturation
        self._lock = sanitize.make_lock(
            "Fragment._lock", reentrant=True,
            inner=saturation.ContendedLock("fragment", reentrant=True),
        )
        self._opened = False  # gates ops-log appends (see _append_op)
        # background compaction hand-off (core/compact.py), injected by
        # the owning View: when set, an over-threshold ops log queues a
        # compaction instead of paying the full snapshot inside the
        # fragment lock on the write path; None = the pre-PR-8 inline
        # snapshot (standalone fragments, tests)
        self._compactor = None
        # snapshot-file generation: bumped (under _lock) every time the
        # file at ``path`` is rewritten. compact() records it before
        # releasing the lock to serialize and aborts its commit if an
        # inline snapshot() (bulk import, anti-entropy merge) rewrote
        # the file meanwhile — welding the NEW file's bytes past a stale
        # base offset onto the clone would commit garbage over it
        self._snap_gen = 0
        # set by drop(): the fragment was relinquished (resize handoff)
        # and its file deleted — late appends and queued compactions
        # must not resurrect it
        self._dropped = False
        # what the last open() recovered: {"tornBytes", "corrupt",
        # "corruptOffset", "quarantined"} — tests and /debug assert on
        # this instead of scraping the log
        self.last_recovery: dict | None = None

        self._np_matrix: np.ndarray | None = None
        self._dirty_rows: set[int] = set()
        self._all_dirty = True
        self._device = None
        # monotone mutation counter; stacked-matrix caches key off
        # (uid, version) so a deleted-and-recreated fragment never
        # aliases a cache entry
        self.version = 0
        self.uid = next(_FRAGMENT_UIDS)
        # set by the owning View: bumps its whole-view mutation stamp so
        # the stack cache can validate a shard list in O(1)
        self._on_mutate = None
        # (version, ids) memo for row_ids(); ids stored as a tuple so a
        # caller mutating its result can't corrupt the memo
        self._row_ids_cache: tuple[int, tuple[int, ...]] | None = None
        # (version, row) log so stacked-matrix caches can apply O(dirty
        # rows) device-side deltas instead of re-uploading the stack;
        # bounded — readers asking about versions older than _dirty_floor
        # get None (= unknown, do a full restack)
        self._dirty_history: list[tuple[int, int]] = []
        self._dirty_floor = 0
        # lazily-computed upper bound on the max set position: n_rows()
        # must be O(1) (the stack-budget check runs per query); adds
        # raise it incrementally, removes leave it stale-high (harmless —
        # overestimates only pad), bulk rewrites reset it
        self._approx_max_pos = -1

    # ----------------------------------------------------------- lifecycle
    def open(self) -> None:
        """Load snapshot + replay ops log (reference: fragment.Open),
        repairing whatever a crash left behind (docs/durability.md):

        - a stale ``.snapshotting`` tmp is discarded — it was never
          renamed in, so the old snapshot at ``path`` is authoritative;
        - a snapshot with a bad roaring header is quarantined to
          ``<path>.corrupt`` and the fragment reopens empty (loudly) —
          the ``.snapshotting``-era recovery rule: never adopt bytes the
          atomic-replace protocol didn't commit;
        - the ops log replays through ``replay_ops_checked``: a torn
          tail truncates cleanly, a checksum mismatch (in-place
          corruption) is reported with fragment path + byte offset and
          everything from the bad record on is truncated — appending
          after a damaged tail would weld the next op onto it."""
        with self._lock:
            if self.path:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._recover()
                if not os.path.exists(self.path):
                    self._write_snapshot()
            self._opened = True
            self._mark_all_dirty()

    def _recover(self) -> None:
        # two tmp names: ".snapshotting" (inline snapshot) and
        # ".compacting" (background fold) — distinct so an inline
        # snapshot landing while a compaction serializes off-lock can
        # never write through the compactor's still-open tmp fd
        for suffix in (".snapshotting", ".compacting"):
            stale_tmp = self.path + suffix
            if os.path.exists(stale_tmp):
                _LOG.log(
                    f"fragment {self.path}: discarding stale {suffix} tmp "
                    "(crash mid-snapshot; previous snapshot is authoritative)"
                )
                os.remove(stale_tmp)
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        if not data:
            return
        rec = {"tornBytes": 0, "corrupt": False, "corruptOffset": -1,
               "quarantined": False}
        self.last_recovery = rec
        try:
            self.bitmap, consumed = roaring.deserialize(data)
        except ValueError as e:
            quarantine = self.path + ".corrupt"
            durable.replace_durable(self.path, quarantine)
            rec["quarantined"] = True
            _LOG.log(
                f"fragment {self.path}: snapshot rejected ({e}); "
                f"quarantined to {quarantine}, reopening empty"
            )
            self.bitmap = roaring.Bitmap()
            self.op_n = 0
            self.ops_bytes = 0
            return
        res = roaring.replay_ops_checked(self.bitmap, data[consumed:])
        self.op_n = res.n_ops
        self.ops_bytes = res.good_bytes
        self.snapshot_bytes = consumed
        good_end = consumed + res.good_bytes
        if res.corrupt:
            rec["corrupt"] = True
            rec["corruptOffset"] = consumed + res.corrupt_offset
            _LOG.log(
                f"fragment {self.path}: ops-log checksum mismatch at "
                f"byte offset {consumed + res.corrupt_offset} — "
                f"truncating the untrusted tail ({len(data) - good_end} "
                "bytes)"
            )
        if good_end < len(data):
            rec["tornBytes"] = len(data) - good_end
            durable.truncate_file(self.path, good_end)

    def close(self) -> None:
        pass  # no retained file handle (see _append_op)

    def _append_op(self, opcode: int, values: np.ndarray) -> None:
        """Ops-log append, open-per-write. A retained append handle per
        fragment exhausts the fd limit at scale: a time field with an
        hourly quantum materializes a fragment per (bucket view, shard) —
        one hourly-taxi import batch opened ~8.4k fragments, two batches
        blew a 20k ulimit. An open/write/close per BATCH (the import path
        is batched) is microseconds against the numpy work, and leaves
        fds in use only while a write is in flight. Gated on open():
        mutating a never-opened pathed fragment must stay in-memory-only
        (appending to a file with no snapshot header would corrupt it).

        Durability: the append goes through ``durable.append_wal`` —
        fsynced per the WAL mode (``always`` inline, ``batch`` at the
        API's ack barrier, ``off`` never). An over-threshold ops log
        queues a BACKGROUND compaction when a compactor is attached;
        the inline snapshot (which pays serialize+fsync+rename inside
        the fragment lock, stalling the write path) remains only for
        standalone fragments."""
        if self.path is None or not self._opened or self._dropped:
            return
        framed = roaring.append_op(opcode, values)
        durable.append_wal(self.path, framed)
        self.op_n += 1
        self.ops_bytes += len(framed)
        self._maybe_fold()

    def _append_union_op(self, frame: bytes) -> None:
        """Ops-log append of one whole roaring frame (the bulk-ingest
        adopt record): same gating/durability rules as ``_append_op``,
        but the payload is the incoming serialized bitmap rather than a
        value vector — an import-roaring post pays ONE crc32-framed WAL
        append (group-fsynced at the ack barrier) instead of the full
        snapshot rewrite it used to pay, and the background Compactor
        folds the accumulated frames off the write path."""
        if self.path is None or not self._opened or self._dropped:
            return
        framed = roaring.append_union_op(frame)
        durable.append_wal(self.path, framed)
        self.op_n += 1
        self.ops_bytes += len(framed)
        self._maybe_fold()

    # byte-debt fold trigger = max(max_op_bytes, FACTOR × snapshot):
    # scaling with the live snapshot bounds write amplification to
    # ~1 + 1/FACTOR and keeps the compactor's GIL-heavy whole-fragment
    # serialize at a low duty cycle under sustained bulk ingest (at
    # FACTOR=1 the fold ran after nearly every frame on a grown
    # fragment, stealing the serving core); crash replay stays within
    # ~FACTOR × the snapshot parse — union-frame replay is a
    # deserialize + container OR pass, far cheaper than the fold
    FOLD_BYTES_FACTOR = 4

    def _maybe_fold(self) -> None:
        # two debt axes, either trips the fold: record count (replay op
        # overhead) and bytes (replay parse volume — union frames can
        # blow past the byte axis in a handful of records)
        if self.op_n > self.max_op_n or self.ops_bytes > max(
            self.max_op_bytes, self.FOLD_BYTES_FACTOR * self.snapshot_bytes
        ):
            if self._compactor is not None:
                self._compactor.request(self, reason="threshold")
            else:
                self.snapshot()

    def snapshot(self) -> None:
        """Durable full rewrite; truncates the ops log (reference:
        fragment.snapshot). Synchronous — holds the fragment lock for
        the whole serialize; the hot write path uses the background
        compactor instead (see _append_op)."""
        with self._lock:
            if self.path is None or self._dropped:
                # dropped: a stale reference's late bulk write (import,
                # anti-entropy merge) must not recreate the relinquished
                # shard's file any more than a queued compaction may
                self.op_n = 0
                self.ops_bytes = 0
                return
            self._write_snapshot()
            self.op_n = 0
            self.ops_bytes = 0

    def _write_snapshot(self) -> None:
        # in-place compaction is safe here: callers hold _lock
        data = roaring.serialize(self.bitmap, compact_in_place=True)
        durable.atomic_write_file(
            self.path, data, tmp_suffix=".snapshotting", op="snapshot-write"
        )
        self.snapshot_bytes = len(data)
        self._snap_gen += 1

    def drop(self) -> None:
        """Mark the fragment relinquished and delete its file (cluster
        resize handoff) — under the fragment lock, so an in-flight
        ``compact()`` commit cannot land its tmp over the freshly
        deleted path and resurrect the shard's data on disk; a
        compaction still queued for this fragment becomes a no-op."""
        with self._lock:
            self._dropped = True
            if self.path and os.path.exists(self.path):
                os.remove(self.path)

    def compact(self) -> bool:
        """Fold the ops log into a fresh snapshot WITHOUT stalling
        writers: the bulk of the work (serializing the bitmap, writing +
        fsyncing the new snapshot) runs outside the fragment lock, so a
        concurrent ``Set()`` only ever waits for the two short locked
        phases (a shallow container-dict clone; the tail carry + rename).

        Protocol — crash-safe at every point (the old snapshot file
        stays valid until the atomic replace commits):

        1. under the lock: shallow-clone the bitmap (containers are
           copy-on-write — every mutator replaces, never edits, a
           container, so sharing them with a live writer is safe),
           record the current file length L and op count;
        2. off the lock: serialize the clone and write it to the
           ``.compacting`` tmp (NOT ``.snapshotting`` — an inline
           snapshot() racing this phase must not rename our half-written
           tmp into place or interleave with our open fd), fsynced;
        3. under the lock: re-check the snapshot generation — an inline
           ``snapshot()`` (bulk import adopt, anti-entropy merge) that
           rewrote the file while we serialized already folded every op,
           and our clone is stale against it, so the commit aborts —
           then copy the ops appended since the clone (the old file's
           bytes past L) onto the tmp, fsync, atomically replace +
           dir-fsync, and subtract the folded ops from op_n.

        Returns True if a snapshot was committed, False on an abort
        (dropped fragment, concurrent inline snapshot won) — the
        compactor counts only real folds.
        """
        with self._lock:
            if self._dropped:
                return False
            if self.path is None:
                self.op_n = 0
                return False
            if not os.path.exists(self.path):
                # never snapshotted (path created mid-teardown?): the
                # inline write is the only correct form
                self._write_snapshot()
                self.op_n = 0
                self.ops_bytes = 0
                return True
            clone = roaring.Bitmap()
            clone._containers = dict(self.bitmap._containers)
            base_len = os.path.getsize(self.path)
            ops_at_clone = self.op_n
            ops_bytes_at_clone = self.ops_bytes
            gen_at_clone = self._snap_gen
        data = roaring.serialize(clone)  # NOT in place: containers shared
        tmp = self.path + ".compacting"
        durable.write_new_file(tmp, data, op="snapshot-write")
        with self._lock:
            if self._dropped or self._snap_gen != gen_at_clone:
                # the file we cloned against is gone (drop) or was
                # rewritten by an inline snapshot that folded everything
                # — bytes past base_len are snapshot payload, not ops;
                # committing would clobber the newer state
                os.remove(tmp)
                return False
            with open(self.path, "rb") as f:
                f.seek(base_len)
                tail = f.read()  # ops appended while we serialized
            if tail:
                durable.append_file(tmp, tail, op="snapshot-write")
            durable.replace_durable(tmp, self.path)
            self.snapshot_bytes = len(data)
            self._snap_gen += 1
            self.op_n -= ops_at_clone
            self.ops_bytes = max(0, self.ops_bytes - ops_bytes_at_clone)
            return True

    # ------------------------------------------------------------- rows
    def n_rows(self) -> int:
        if not self.bitmap._containers:
            return 0
        if self._approx_max_pos < 0:
            self._approx_max_pos = int(self.bitmap.max())
        return self._approx_max_pos // SHARD_WIDTH + 1

    def _raise_max_pos(self, pos: int) -> None:
        if self._approx_max_pos >= 0:
            self._approx_max_pos = max(self._approx_max_pos, int(pos))

    def _candidate_rows(self) -> list[int]:
        """Sorted row IDs that MAY hold bits, derived from container keys
        (each key covers 2^16 positions; a key's span may overlap several
        rows when SHARD_WIDTH < 2^16) — no full scan."""
        candidates: set[int] = set()
        for key in self.bitmap._containers.keys():
            first = (key << 16) // SHARD_WIDTH
            last = ((key + 1) << 16) - 1
            candidates.update(range(first, last // SHARD_WIDTH + 1))
        return sorted(candidates)

    def row_ids(self) -> list[int]:
        """Row IDs with ≥1 bit set (reference: fragment.rows). Memoized
        per mutation version — Rows/GroupBy/TopN consult this on every
        query and the candidate scan + per-row range_count is O(rows)."""
        with self._lock:
            cached = self._row_ids_cache
            if cached is not None and cached[0] == self.version:
                return list(cached[1])
            ids = [
                r
                for r in self._candidate_rows()
                if self.bitmap.range_count(r * SHARD_WIDTH, (r + 1) * SHARD_WIDTH)
            ]
            self._row_ids_cache = (self.version, tuple(ids))
            return ids

    def row_columns(self, row: int) -> np.ndarray:
        """Absolute column IDs set in a row, ascending (uint64)."""
        start = row * SHARD_WIDTH
        rel = self.bitmap.range_values(start, start + SHARD_WIDTH) - np.uint64(start)
        return rel + np.uint64(self.shard * SHARD_WIDTH)

    def row_packed(self, row: int) -> np.ndarray:
        start = row * SHARD_WIDTH
        return roaring.pack_range(self.bitmap, start, start + SHARD_WIDTH)

    def row_count(self, row: int) -> int:
        start = row * SHARD_WIDTH
        return self.bitmap.range_count(start, start + SHARD_WIDTH)

    # --------------------------------------------------------- mutation
    def _pos(self, row: int, col: int) -> int:
        return row * SHARD_WIDTH + (col % SHARD_WIDTH)

    def set_bit(self, row: int, col: int) -> bool:
        with self._lock:
            pos = self._pos(row, col)
            changed = self.bitmap.add(pos)
            if changed:
                self._append_op(roaring.OP_ADD, np.array([pos], dtype=np.uint64))
                self._raise_max_pos(pos)
                self._mark_dirty(row)
            return changed

    def clear_bit(self, row: int, col: int) -> bool:
        with self._lock:
            pos = self._pos(row, col)
            changed = self.bitmap.remove(pos)
            if changed:
                self._append_op(roaring.OP_REMOVE, np.array([pos], dtype=np.uint64))
                self._mark_dirty(row)
            return changed

    def contains(self, row: int, col: int) -> bool:
        return self.bitmap.contains(self._pos(row, col))

    def clear_row(self, row: int) -> bool:
        """Remove every bit in a row (PQL ClearRow)."""
        with self._lock:
            start = row * SHARD_WIDTH
            positions = self.bitmap.range_values(start, start + SHARD_WIDTH)
            if positions.size == 0:
                return False
            self.bitmap.remove_many(positions)
            self._append_op(roaring.OP_REMOVE, positions)
            self._mark_dirty(row)
            return True

    def set_row(self, row: int, columns: np.ndarray) -> bool:
        """Replace a row's contents with ``columns`` (in-shard positions;
        PQL Store)."""
        with self._lock:
            self.clear_row(row)
            if columns.size:
                positions = (
                    np.asarray(columns, dtype=np.uint64) % SHARD_WIDTH
                ) + np.uint64(row * SHARD_WIDTH)
                self.bitmap.add_many(positions)
                self._append_op(roaring.OP_ADD, positions)
                self._raise_max_pos(int(positions.max()))
            self._mark_dirty(row)
            return True

    def rows_containing(self, col: int) -> list[int]:
        """Rows whose bit for ``col`` is set (mutex/bool single-value
        enforcement; reference: fragment mutex handling). Only candidate
        rows (≥1 bit anywhere) are probed, all through one vectorized
        ``contains_many`` call — never a Python loop up to n_rows()."""
        cand = self._candidate_rows()
        if not cand:
            return []
        c = col % SHARD_WIDTH
        rids = np.asarray(cand, dtype=np.uint64)
        hit = self.bitmap.contains_many(
            rids * np.uint64(SHARD_WIDTH) + np.uint64(c)
        )
        return [int(r) for r in rids[hit]]

    def bulk_import(self, rows: np.ndarray, cols: np.ndarray, clear: bool = False) -> None:
        """Batched set/clear (reference: fragment.bulkImport). ``cols`` are
        absolute or in-shard column IDs; reduced mod SHARD_WIDTH. Empty
        batches are free (no ops-log record, no cache work)."""
        with self._lock:
            rows = np.asarray(rows, dtype=np.uint64)
            if rows.size == 0:
                return
            cols = np.asarray(cols, dtype=np.uint64) % np.uint64(SHARD_WIDTH)
            positions = rows * np.uint64(SHARD_WIDTH) + cols
            if clear:
                self.bitmap.remove_many(positions)
                self._append_op(roaring.OP_REMOVE, positions)
            else:
                self.bitmap.add_many(positions)
                self._append_op(roaring.OP_ADD, positions)
                self._raise_max_pos(int(positions.max()))
            for r in np.unique(rows).tolist():
                self._mark_dirty(int(r))

    def mutex_import(self, rows: np.ndarray, cols: np.ndarray) -> None:
        """Batched single-value (mutex/bool) import: for every imported
        column, clear the bit in every OTHER row, then set the target bit
        (reference: fragment.go mutex handling — which does it bit by
        bit; here one vectorized pass over the fragment's value set).

        ``cols`` must be deduplicated (last-wins resolved by the caller);
        they may be absolute or in-shard (reduced mod SHARD_WIDTH).
        """
        with self._lock:
            rows = np.asarray(rows, dtype=np.uint64)
            if rows.size == 0:
                return
            rel = np.asarray(cols, dtype=np.uint64) % np.uint64(SHARD_WIDTH)
            # conflict scan, cost-adaptive: a big batch scans the whole
            # value set once (O(total bits)); a small batch against a big
            # fragment probes only the candidate (existing row × imported
            # column) grid via vectorized membership (O(rows·batch))
            existing_rows = self.row_ids()
            total_bits = self.bitmap.count()
            if total_bits <= len(existing_rows) * rel.size:
                vals = self.bitmap.range_values(0, self.n_rows() * SHARD_WIDTH)
                order = np.argsort(rel)
                rel_s, tgt_s = rel[order], rows[order]
                to_remove = np.empty(0, dtype=np.uint64)
                if vals.size:
                    vrows = vals // np.uint64(SHARD_WIDTH)
                    vcols = vals % np.uint64(SHARD_WIDTH)
                    at = np.searchsorted(rel_s, vcols)
                    at_c = np.minimum(at, rel_s.size - 1)
                    hit = rel_s[at_c] == vcols
                    conflict = hit & (vrows != tgt_s[at_c])
                    to_remove = vals[conflict]
            else:
                rids = np.asarray(existing_rows, dtype=np.uint64)
                cand = (
                    rids[:, None] * np.uint64(SHARD_WIDTH) + rel[None, :]
                ).ravel()
                hit = self.bitmap.contains_many(cand).reshape(
                    rids.size, rel.size
                )
                conflict = hit & (rids[:, None] != rows[None, :])
                to_remove = cand.reshape(rids.size, rel.size)[conflict]
            if to_remove.size:
                self.bitmap.remove_many(to_remove)
                self._append_op(roaring.OP_REMOVE, to_remove)
                for r in np.unique(to_remove // np.uint64(SHARD_WIDTH)).tolist():
                    self._mark_dirty(int(r))
            positions = rows * np.uint64(SHARD_WIDTH) + rel
            self.bitmap.add_many(positions)
            self._append_op(roaring.OP_ADD, positions)
            self._raise_max_pos(int(positions.max()))
            for r in np.unique(rows).tolist():
                self._mark_dirty(int(r))

    def import_roaring(self, data: bytes) -> "roaring.Bitmap":
        """Union a serialized roaring bitmap of fragment-relative positions
        straight into storage (reference: fragment.importRoaring fast
        path). Durability is ONE crc32-framed union-op WAL append of the
        incoming frame (group-fsynced at the caller's ack barrier) — NOT
        a full snapshot rewrite per post: the pre-r14 inline snapshot
        paid serialize+fsync+rename of the whole merged fragment inside
        the lock on every import, which is exactly what capped sustained
        ingest at demo speed. The background Compactor folds the
        accumulated frames off the write path (``_maybe_fold``).

        Returns the INCOMING bitmap (the delta, pre-union) so callers
        that derive follow-up work from the import — existence-field
        marking in api.ImportRoaring — stay O(delta) instead of
        re-enumerating the whole merged fragment per call. Returning the
        bitmap (not materialized values) keeps the return free for
        callers that ignore it; on the adopt path the caller must treat
        it as read-only — it IS the fragment's storage."""
        with self._lock:
            incoming, consumed = roaring.deserialize(data)
            roaring.replay_ops(incoming, data[consumed:])
            if not self.bitmap._containers:
                # fresh fragment: adopt the deserialized bitmap outright
                # (zero-copy buffer views) — the dominant bulk-load case
                self.bitmap = incoming
            else:
                self.bitmap = self.bitmap | incoming
            self._append_union_op(data)
            self._mark_all_dirty()
            return incoming

    def union_positions(self, positions: np.ndarray) -> None:
        """Bulk-OR fragment-relative positions: the import_roaring merge
        without the wire codec — build the delta's containers vectorized,
        then ``union_bitmap``. O(delta); for deltas past the ops-log
        threshold this beats the per-op bit-list path by an order of
        magnitude, and the logged frame is far smaller than an OP_ADD
        record's 8 bytes/bit for dense deltas."""
        positions = np.asarray(positions, dtype=np.uint64)
        if positions.size == 0:
            return
        incoming = roaring.Bitmap()
        incoming.add_many(positions)
        self.union_bitmap(incoming)

    def union_bitmap(self, incoming: "roaring.Bitmap") -> None:
        """Union a PRE-BUILT delta bitmap into storage (the existence-
        marking fast path: the adopt delta's column set is folded
        container-wise, never re-sorted — docs/ingest.md). Durability is
        one compressed union-frame WAL append, like import_roaring. The
        caller must hand over ownership: containers may be adopted by
        reference."""
        if not incoming._containers:
            return
        with self._lock:
            frame = roaring.serialize(incoming)
            if not self.bitmap._containers:
                self.bitmap = incoming
            else:
                self.bitmap = self.bitmap | incoming
            self._append_union_op(frame)
            self._mark_all_dirty()

    DIRTY_HISTORY_MAX = 4096

    def _mark_dirty(self, row: int) -> None:
        self._dirty_rows.add(row)
        self._device = None
        self.version += 1
        self._dirty_history.append((self.version, row))
        if len(self._dirty_history) > self.DIRTY_HISTORY_MAX:
            drop = len(self._dirty_history) // 2
            self._dirty_floor = self._dirty_history[drop - 1][0]
            del self._dirty_history[:drop]
        if self._on_mutate is not None:
            self._on_mutate()

    def _mark_all_dirty(self) -> None:
        """Bulk/out-of-band rewrite: delta tracking restarts here."""
        self._approx_max_pos = -1
        self._all_dirty = True
        self._device = None
        self.version += 1
        self._dirty_history.clear()
        self._dirty_floor = self.version
        if self._on_mutate is not None:
            self._on_mutate()

    def dirty_rows_since(self, version: int) -> set[int] | None:
        """Rows dirtied after ``version``, or None when unknowable (the
        history was trimmed, or a bulk rewrite happened)."""
        with self._lock:
            if version < self._dirty_floor:
                return None
            return {r for v, r in self._dirty_history if v > version}

    def rebuild_cache(self) -> None:
        """Opt-in full rebuild — see the module docstring's rank-cache
        policy; no hot path calls this."""
        self.cache.clear()
        if isinstance(self.cache, NopCache):
            return
        for r in self.row_ids():
            self.cache.add(r, self.row_count(r))

    # ----------------------------------------------------------- device
    def host_matrix(self) -> tuple[np.ndarray, int]:
        """(np uint32[R_pad, W], n_rows) — packed matrix on host, with
        dirty rows repacked incrementally. The stacked-query path reads
        this directly (one upload for the whole stack) instead of paying
        a per-fragment device round trip."""
        with self._lock:
            n = max(self.n_rows(), 1)
            r_pad = _pad_rows(n)
            if (
                self._np_matrix is None
                or self._all_dirty
                or self._np_matrix.shape[0] < n
            ):
                m = np.zeros((r_pad, WORDS_PER_SHARD), dtype=np.uint32)
                for r in self.row_ids():
                    m[r] = self.row_packed(r)
                self._np_matrix = m
                self._all_dirty = False
                self._dirty_rows.clear()
                self._device = None
            elif self._dirty_rows:
                for r in self._dirty_rows:
                    if r < self._np_matrix.shape[0]:
                        self._np_matrix[r] = self.row_packed(r)
                self._dirty_rows.clear()
                self._device = None
            return self._np_matrix, n

    def device_matrix(self):
        """(jax uint32[R_pad, W], n_rows) — packed matrix on device;
        uploaded only when something changed since the last call."""
        import jax.numpy as jnp  # deferred: keep host paths importable fast

        with self._lock:
            m, n = self.host_matrix()
            if self._device is None:
                self._device = jnp.asarray(m)
            return self._device, n

    # ------------------------------------------------------ anti-entropy
    def block_checksums(self) -> list[tuple[int, bytes]]:
        """[(block_id, checksum)] over 100-row blocks with any bits set
        (reference: fragment.blocks). Used by the holder syncer to diff
        replicas cheaply."""
        out = []
        rows = self.row_ids()
        if not rows:
            return out
        blocks: dict[int, list[int]] = {}
        for r in rows:
            blocks.setdefault(r // ROWS_PER_BLOCK, []).append(r)
        for block_id in sorted(blocks):
            h = hashlib.blake2b(digest_size=16)
            start = block_id * ROWS_PER_BLOCK * SHARD_WIDTH
            stop = (block_id + 1) * ROWS_PER_BLOCK * SHARD_WIDTH
            h.update(self.bitmap.range_values(start, stop).tobytes())
            out.append((block_id, h.digest()))
        return out

    def block_data(self, block_id: int) -> tuple[np.ndarray, np.ndarray]:
        """(row_ids, in-shard columns) for one block (reference:
        fragment.blockData)."""
        start = block_id * ROWS_PER_BLOCK * SHARD_WIDTH
        stop = (block_id + 1) * ROWS_PER_BLOCK * SHARD_WIDTH
        positions = self.bitmap.range_values(start, stop)
        rows = positions // np.uint64(SHARD_WIDTH)
        cols = positions % np.uint64(SHARD_WIDTH)
        return rows, cols

    def merge_block(self, block_id: int, rows: np.ndarray, cols: np.ndarray) -> None:
        """Replace one block's contents with the reconciled (rows, cols)
        (anti-entropy repair: reference holder_syncer block merge)."""
        with self._lock:
            start = block_id * ROWS_PER_BLOCK * SHARD_WIDTH
            stop = (block_id + 1) * ROWS_PER_BLOCK * SHARD_WIDTH
            existing = self.bitmap.range_values(start, stop)
            incoming = (
                np.asarray(rows, dtype=np.uint64) * np.uint64(SHARD_WIDTH)
                + np.asarray(cols, dtype=np.uint64)
            )
            self.bitmap.remove_many(existing)
            self.bitmap.add_many(incoming)
            self.snapshot()
            self._mark_all_dirty()
