"""Background ops-log → snapshot compaction.

The reference snapshots a fragment INLINE when its ops log passes
MAX_OP_N (fragment.go snapshot) — which means one unlucky ``Set()`` pays
a full serialize+fsync+rename inside the fragment lock, stalling every
writer behind it. Here that work moves to a bounded worker pool
(docs/durability.md): ``Fragment._append_op`` queues the fragment and
returns; the worker runs ``Fragment.compact()``, whose locked phases are
O(containers) + O(ops-since-clone) — writes continue against the live
bitmap and ops log throughout, and a crash mid-compaction leaves the old
snapshot valid (the ``.compacting`` tmp is only committed by the
atomic replace).

Backpressure: ``debt()`` (queued + in-flight compactions) feeds the
event front end's write lane — past ``compaction-max-debt`` new write
requests get 429 + Retry-After instead of growing the queue without
bound (the ops logs, and therefore replay time after a crash, grow with
the debt).

Observability: ``compaction_pending`` gauge, ``compactions_total{reason}``
counter, ``compaction.run`` trace spans.
"""

from __future__ import annotations

import threading
from collections import deque

from pilosa_tpu.utils import GLOBAL_TRACER
from pilosa_tpu.utils.durable import SimulatedCrash
from pilosa_tpu.utils.log import Logger


class Compactor:
    """Bounded compaction worker pool with a per-fragment-deduped FIFO.

    One fragment is compacted by one worker at a time (the dedupe keys
    on the fragment uid and an entry stays claimed until its run
    finishes), so concurrent threshold trips cannot double-compact."""

    def __init__(self, workers: int = 1, stats=None, logger: Logger | None = None):
        self.workers = max(1, int(workers))
        self.stats = stats
        self.log = (logger or Logger()).log
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._queued: set[int] = set()  # fragment uids in _queue
        self._inflight: set[int] = set()
        self._threads: list[threading.Thread] = []
        self._closed = False
        self.compacted = 0
        self.failed = 0
        self.crashed = 0

    # ------------------------------------------------------------ control
    def start(self) -> None:
        with self._lock:
            if self._threads or self._closed:
                return
            for i in range(self.workers):
                t = threading.Thread(
                    target=self._run, daemon=True, name=f"compactor-{i}"
                )
                self._threads.append(t)
                t.start()

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        if drain:
            self.wait_idle(timeout)
        with self._lock:
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)

    # ------------------------------------------------------------- intake
    def request(self, fragment, reason: str = "threshold") -> bool:
        """Queue one compaction; deduped — a fragment already queued or
        in flight is not queued again (its queued run will fold the new
        ops too, or its next threshold trip re-queues it). Lazily starts
        the workers so a Holder used without a Server still compacts."""
        if getattr(fragment, "_dropped", False):
            return False  # relinquished in a resize handoff; file is gone
        # capture the REQUESTING thread's trace context: the compaction
        # this write triggered runs on a background worker, but its
        # compaction.run span must join the originating query's trace —
        # a slow query whose write tripped a compaction is only
        # self-explaining if the trace shows the compaction it caused
        ctx = GLOBAL_TRACER.current_context()
        with self._lock:
            if self._closed:
                return False
            if fragment.uid in self._queued or fragment.uid in self._inflight:
                return False
            self._queue.append((fragment, reason, ctx))
            self._queued.add(fragment.uid)
            self._cond.notify()
            started = bool(self._threads)
        if not started:
            self.start()
        self._gauge()
        return True

    def debt(self) -> int:
        """Queued + in-flight compactions — the write-lane backpressure
        signal (config ``compaction-max-debt``)."""
        with self._lock:
            return len(self._queue) + len(self._inflight)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the queue and every worker are idle (tests, and
        drain-on-close so shutdown doesn't abandon queued folds)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: not self._queue and not self._inflight, timeout
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "workers": self.workers,
                "pending": len(self._queue) + len(self._inflight),
                "compacted": self.compacted,
                "failed": self.failed,
                "crashed": self.crashed,
            }

    # ------------------------------------------------------------- worker
    def _gauge(self) -> None:
        if self.stats is not None:
            self.stats.gauge("compaction_pending", float(self.debt()))

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                fragment, reason, ctx = self._queue.popleft()
                self._queued.discard(fragment.uid)
                self._inflight.add(fragment.uid)
            ok = False
            try:
                ok = self._compact_one(fragment, reason, ctx)
            finally:
                # a write burst that outran the fold leaves the ops log
                # over threshold with no future append to re-queue it —
                # follow up now, but ONLY after a successful fold (a
                # failing disk must not hot-loop the worker; the failed
                # fragment retries on its next append). Same lock as
                # the in-flight discard so wait_idle can't observe an
                # idle gap before the follow-up is queued.
                requeue = (
                    ok
                    and not self._closed
                    and fragment.op_n > fragment.max_op_n
                )
                with self._lock:
                    self._inflight.discard(fragment.uid)
                    if requeue and fragment.uid not in self._queued:
                        # follow-up of the same trigger: keep the
                        # originating context so the whole fold chain
                        # stays navigable from one trace
                        self._queue.append((fragment, "followup", ctx))
                        self._queued.add(fragment.uid)
                        self._cond.notify()
                    self._cond.notify_all()
                self._gauge()

    def _compact_one(self, fragment, reason: str, ctx=None) -> bool:
        try:
            # join the trace of the write that queued this compaction
            # (ctx is (trace_id, span_id) captured at request time);
            # detached() also isolates the worker from any leftover
            # span state on this thread
            tid, parent = ctx if ctx else (None, None)
            with GLOBAL_TRACER.detached(tid, parent):
                with GLOBAL_TRACER.span(
                    "compaction.run",
                    path=str(fragment.path),
                    reason=reason,
                    op_n=fragment.op_n,
                ):
                    committed = bool(fragment.compact())
            if committed:
                # counted ONLY on a real fold: an aborted commit (the
                # fragment was dropped, or an inline snapshot won the
                # race and folded everything itself) must not inflate
                # compactions_total / the bench's compactor-ran gate
                with self._lock:
                    self.compacted += 1
                if self.stats is not None:
                    self.stats.count(
                        "compactions_total", tags={"reason": reason}
                    )
            return committed
        except SimulatedCrash:
            # a fault-injected process death reached the worker instead
            # of killing the process (the in-process chaos suite): the
            # old snapshot is still valid on disk — record it and leave
            # recovery to whoever reopens the holder
            with self._lock:
                self.crashed += 1
            if self.stats is not None:
                self.stats.count("compactions_crashed")
        except Exception as e:  # pilosa: allow(broad-except) — worker
            # containment: EIO/ENOSPC from the disk (or the fault layer)
            # is the expected shape, but ANY unexpected error (a
            # serialize limit, a codec bug) must not kill the daemon
            # worker — with one worker dead, debt grows past
            # compaction-max-debt and the write lane 429s forever. The
            # old snapshot stays authoritative; the ops log keeps
            # growing, so the next threshold trip retries — debt-driven
            # write backpressure bounds how far that can run away.
            with self._lock:
                self.failed += 1
            if self.stats is not None:
                self.stats.count("compactions_failed")
            self.log(f"compaction failed for {fragment.path}: {e!r}")
        return False
