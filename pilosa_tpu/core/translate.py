"""String key ↔ uint64 ID translation.

Reference: translate.go (TranslateStore, TranslateFile — an append-only
mmap log; primary writes, replicas tail). Here: an in-memory dict pair with
an append-only JSON-lines log for durability and replication tailing (the
log offset is the replication cursor — see the cluster layer).

One store instance serves either an index's column keys or one field's row
keys (reference keeps per-index and per-field maps in one file; separate
files are simpler and shard-friendly).
"""

from __future__ import annotations

import json
import os
import threading

from pilosa_tpu.utils import durable, sanitize
from pilosa_tpu.utils.log import Logger

_LOG = Logger()  # stderr sink; recovery events must be loud


class TranslateStore:
    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = sanitize.make_lock("TranslateStore._lock", reentrant=True)
        self._by_key: dict[str, int] = {}
        self._by_id: dict[int, str] = {}
        self._next_id = 1  # 0 is reserved (reference never allocates 0)
        # highest id N such that ids 1..N are ALL present — except the
        # ids listed in _holes. Replica tailing must resume from this
        # watermark, not max(_by_id): a hole below max (a missed primary
        # push) would otherwise never be refilled.
        self._dense_through = 0
        # ids ≤ _dense_through with NO local binding: vacated by a fork
        # displacement. Tracked explicitly (instead of clamping the
        # watermark below them) so incremental tailing stays O(new):
        # a clamped watermark under a permanent hole re-ships the entire
        # tail above it on EVERY sync pass. Pulls request hole ids
        # explicitly, so a binding the surviving chain issues for a hole
        # id later still arrives (see entries_from(holes=...)).
        self._holes: set[int] = set()
        self._hole_pull_cursor = 0
        self._file = None

    def open(self) -> None:
        with self._lock:
            if self.path is None:
                return
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            if os.path.exists(self.path):
                with open(self.path, "rb") as f:
                    raw = f.read()
                good = 0  # byte offset of the last complete good line
                for line_b in raw.splitlines(keepends=True):
                    if not line_b.endswith(b"\n"):
                        break  # torn tail: the line never completed
                    line = line_b.strip()
                    if line:
                        try:
                            entry = json.loads(line)
                        except (json.JSONDecodeError, UnicodeDecodeError):
                            break  # torn/corrupt record
                        # replay with displacement: the log may record a
                        # fork reconciliation (winning entry appended
                        # after the stale one) — last write wins cleanly
                        self._apply_displacing(entry["k"], entry["id"], [])
                    good += len(line_b)
                if good < len(raw):
                    # truncate the untrusted tail BEFORE reopening for
                    # append: a new record welded onto a partial line
                    # would make one unparseable line, and the NEXT
                    # reopen would silently drop every acknowledged
                    # binding appended after the weld
                    _LOG.log(
                        f"translate log {self.path}: discarding "
                        f"{len(raw) - good} torn/corrupt tail byte(s) "
                        f"at offset {good}"
                    )
                    durable.truncate_file(self.path, good)
            # retained append handle (allocation rate makes open-per-
            # write measurable here); binary mode so the batched append
            # helper (durable.wal_write) can apply torn-write fault caps
            # on raw bytes. Durability bookkeeping happens once per
            # flushed BATCH via durable.wal_written.
            self._file = durable.open_wal(self.path, "ab")

    def close(self) -> None:
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None

    def _apply(self, key: str, id_: int) -> None:
        self._by_key[key] = id_
        self._by_id[id_] = key
        self._next_id = max(self._next_id, id_ + 1)
        self._holes.discard(id_)  # a late binding fills the gap
        self._advance_watermark()

    @property
    def dense_through(self) -> int:
        """Replica tailing cursor: every id ≤ this is present locally,
        except the ids in holes()."""
        with self._lock:
            return self._dense_through

    def holes(self) -> list[int]:
        """Ids vacated by fork displacements and not since re-bound. A
        tailing pull must request the ones at/below its offset
        explicitly — they are invisible to an `id > offset` scan (the
        sender ignores requested holes above the offset: the tail scan
        already covers those)."""
        with self._lock:
            return sorted(self._holes)

    def translate_key(self, key: str, create: bool = True) -> int | None:
        """key → ID, allocating when ``create`` (reference:
        TranslateStore.TranslateColumnsToUint64)."""
        return self.translate_keys([key], create=create)[0]

    def translate_keys(self, keys: list[str], create: bool = True) -> list[int | None]:
        """Batched key → ID translation: one lock acquisition, one WAL
        append (all new bindings joined into a single buffer), one flush
        and one group-commit mark for the WHOLE batch — the per-key
        write/flush/fsync-mark loop made keyed imports pay a durability
        round per rowKey/columnKey (docs/ingest.md). The API façade's
        ``ack_barrier`` after the request is the one fsync point either
        way."""
        with self._lock:
            out: list[int | None] = []
            new_lines: list[str] = []
            for key in keys:
                id_ = self._by_key.get(key)
                if id_ is None and create:
                    id_ = self._next_id
                    self._apply(key, id_)
                    new_lines.append(json.dumps({"k": key, "id": id_}))
                out.append(id_)
            if new_lines and self._file:
                durable.wal_write(
                    self._file, "\n".join(new_lines) + "\n", self.path
                )
            return out

    def translate_id(self, id_: int) -> str | None:
        with self._lock:
            return self._by_id.get(id_)

    def translate_ids(self, ids: list[int]) -> list[str | None]:
        with self._lock:
            return [self._by_id.get(i) for i in ids]

    # ------------------------------------------------- replication support
    def _advance_watermark(self) -> None:
        """Advance dense_through across present ids AND recorded holes
        (callers hold self._lock)."""
        while (nxt := self._dense_through + 1) in self._by_id or (
            nxt in self._holes
        ):
            self._dense_through += 1

    def adopt_holes(self, ids: list[int]) -> None:
        """Adopt a SENDER's known holes (fork vacancies) for ids this
        store has no binding for. Without this, a node that never saw
        the displacement locally — e.g. one that full-pulled after the
        fork — has its watermark stuck below the cluster-wide vacancy
        and re-ships the whole tail above it on every sync."""
        with self._lock:
            for i in ids:
                if i not in self._by_id:
                    self._holes.add(i)
            self._advance_watermark()

    def holes_for_pull(self, limit: int = 1024) -> list[int]:
        """A bounded, ROTATING slice of the hole set to request on an
        incremental pull. Permanent cluster-wide vacancies are never
        dropped (a node with a stale view of who holds what could
        otherwise tombstone an id the surviving chain actually binds —
        permanent divergence); instead the per-pull overhead is capped
        and every hole is retried within ceil(n/limit) passes."""
        with self._lock:
            if not self._holes:
                return []
            ordered = sorted(self._holes)
            if len(ordered) <= limit:
                return ordered
            start = self._hole_pull_cursor % len(ordered)
            self._hole_pull_cursor = (start + limit) % len(ordered)
            window = ordered[start : start + limit]
            if len(window) < limit:  # wrap
                window += ordered[: limit - len(window)]
            return window

    SENDER_HOLES_MAX = 4096

    def tail_for(
        self, offset: int, requested_holes: list[int] | None = None
    ) -> tuple[list[tuple[str, int]], list[int]]:
        """The full tailing answer: (entries, own_holes). ``entries``
        are bindings with id > offset plus any binding held for a
        requested hole id; ``own_holes`` are this store's known
        vacancies ABOVE the offset, for the puller to adopt — holes at
        or below the puller's cursor are either bound on the puller or
        already its own holes, so shipping them is pure payload. Capped;
        an over-cap remainder reaches the puller on later pulls (its
        offset advances past the holes it already adopted)."""
        entries = self.entries_from(offset, holes=requested_holes)
        with self._lock:
            own = sorted(i for i in self._holes if i > offset)[
                : self.SENDER_HOLES_MAX
            ]
        return entries, own

    def entries_from(
        self, offset: int, holes: list[int] | None = None
    ) -> list[tuple[str, int]]:
        """All (key, id) pairs after a cursor for replica tailing
        (reference: /internal/translate/data streaming). ``holes`` lists
        ids at/below the caller's cursor that the caller lacks (fork
        vacancies): any binding this store holds for them is included,
        since an `id > offset` scan can never deliver those again."""
        with self._lock:
            span = self._next_id - 1 - offset
            if 0 < span <= 4 * len(self._by_id):
                # dense-allocation common case: walking (offset, next_id)
                # is O(tail) — sorting the whole map made every
                # incremental heartbeat sync O(n log n) in keyspace size
                tail = []
                for i in range(offset + 1, self._next_id):
                    k = self._by_id.get(i)
                    if k is not None:
                        tail.append((k, i))
            elif span > 0:
                # a sparse high push binding jumped next_id far past the
                # held ids: scanning the gap would be O(next_id), worse
                # than sorting what we actually hold
                tail = [
                    (k, i)
                    for i, k in sorted(self._by_id.items())
                    if i > offset
                ]
            else:
                tail = []
            for i in sorted(set(holes or ())):
                if i <= offset:
                    k = self._by_id.get(i)
                    if k is not None:
                        tail.append((k, i))
            return tail

    def apply_entries(
        self, entries: list[tuple[str, int]]
    ) -> list[tuple[str, int]]:
        """Apply replicated entries; the incoming (primary-chain) binding
        WINS conflicts. Returns the local bindings that were displaced —
        non-empty only after a keyspace fork (a deposed primary's
        never-replicated allocations colliding with the surviving chain),
        so callers log them. Reference: translate.go replicas tail the
        primary verbatim and can't conflict; this store can, because it
        supports primary failover (see cluster._ensure_translate_primacy).
        """
        dropped: list[tuple[str, int]] = []
        with self._lock:
            new_lines: list[str] = []
            for key, id_ in entries:
                if self._apply_displacing(key, id_, dropped):
                    new_lines.append(json.dumps({"k": key, "id": id_}))
            if new_lines and self._file:
                # one batched append + one group-commit mark, like
                # translate_keys — replication apply is the same lane
                durable.wal_write(
                    self._file, "\n".join(new_lines) + "\n", self.path
                )
        return dropped

    def _apply_displacing(
        self, key: str, id_: int, dropped: list[tuple[str, int]]
    ) -> bool:
        """_apply plus removal of any binding the new entry displaces
        (appended to ``dropped``). Returns False when the entry was
        already present (callers skip the log write, keeping it O(delta)).
        """
        old_key = self._by_id.get(id_)
        if old_key == key:
            return False
        if old_key is not None:
            dropped.append((old_key, id_))
            del self._by_key[old_key]
        old_id = self._by_key.get(key)
        if old_id is not None and old_id != id_:
            dropped.append((key, old_id))
            if self._by_id.get(old_id) == key:
                del self._by_id[old_id]
                # the removal punches a hole: record it (tailing requests
                # hole ids explicitly; the watermark advance may cross
                # recorded holes) instead of clamping the watermark — a
                # permanent fork hole would otherwise pin the watermark
                # forever and make every incremental sync re-ship the
                # whole tail above it. Unconditional: a vacancy ABOVE the
                # watermark would equally block the advance when later
                # ids fill in around it.
                self._holes.add(old_id)
        self._apply(key, id_)
        return True
