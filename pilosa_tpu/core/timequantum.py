"""Time-quantum view naming and range cover.

Reference: time.go (TimeQuantum, viewsByTime, viewsByTimeRange) — time
fields materialize one view per calendar bucket (Y/M/D/H) so time-bounded
Row queries read a minimal set of pre-bucketed views instead of filtering.

View names: ``<base>_2018``, ``<base>_201801``, ``<base>_20180102``,
``<base>_2018010203`` for Y/M/D/H buckets.
"""

from __future__ import annotations

from datetime import datetime, timedelta

VALID_UNITS = "YMDH"
_FORMATS = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}
_NAME_LENGTHS = {4: "Y", 6: "M", 8: "D", 10: "H"}


def validate_quantum(q: str) -> str:
    """A quantum is a contiguous run of 'YMDH' (e.g. 'YMD', 'MDH', 'D')."""
    if not q:
        return q
    if q not in ("Y", "M", "D", "H", "YM", "MD", "DH", "YMD", "MDH", "YMDH"):
        raise ValueError(f"invalid time quantum {q!r}")
    return q


def view_by_time_unit(base: str, t: datetime, unit: str) -> str:
    return f"{base}_{t.strftime(_FORMATS[unit])}"


def views_by_time(base: str, t: datetime, quantum: str) -> list[str]:
    """All bucket views a timestamped write lands in (reference:
    viewsByTime) — one per unit present in the quantum."""
    return [view_by_time_unit(base, t, u) for u in quantum]


def _truncate(t: datetime, unit: str) -> datetime:
    if unit == "Y":
        return t.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    if unit == "M":
        return t.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    if unit == "D":
        return t.replace(hour=0, minute=0, second=0, microsecond=0)
    return t.replace(minute=0, second=0, microsecond=0)


def _next(t: datetime, unit: str) -> datetime:
    if unit == "Y":
        return t.replace(year=t.year + 1)
    if unit == "M":
        return (
            t.replace(year=t.year + 1, month=1)
            if t.month == 12
            else t.replace(month=t.month + 1)
        )
    if unit == "D":
        return t + timedelta(days=1)
    return t + timedelta(hours=1)


def parse_view_bucket(view_name: str, base: str) -> tuple[datetime, datetime] | None:
    """(bucket start, bucket end) of a time view name, or None for the
    standard / non-time views. Used to bound open-ended range queries to
    the data that actually exists."""
    prefix = base + "_"
    if not view_name.startswith(prefix):
        return None
    suffix = view_name[len(prefix) :]
    unit = _NAME_LENGTHS.get(len(suffix))
    if unit is None or not suffix.isdigit():
        return None
    try:
        t = datetime.strptime(suffix, _FORMATS[unit])
    except ValueError:
        return None
    return t, _next(t, unit)


def views_by_time_range(base: str, start: datetime, end: datetime, quantum: str) -> list[str]:
    """Minimal set of bucket views covering [start, end) (reference:
    viewsByTimeRange). Greedy: at each step take the coarsest quantum unit
    that is aligned at the cursor and fully contained in the range.
    Endpoints are truncated to the finest unit in the quantum.
    """
    if not quantum:
        raise ValueError("field has no time quantum")
    units = [u for u in VALID_UNITS if u in quantum]  # coarse → fine
    finest = units[-1]
    t = _truncate(start, finest)
    end = _truncate(end, finest) if end == _truncate(end, finest) else _next(
        _truncate(end, finest), finest
    )
    views: list[str] = []
    while t < end:
        for u in units:
            if _truncate(t, u) == t and _next(t, u) <= end:
                views.append(view_by_time_unit(base, t, u))
                t = _next(t, u)
                break
        else:
            # cursor not aligned even at the finest unit — cannot happen
            # after truncation, but guard against infinite loops
            t = _next(t, finest)
    return views
