"""Per-fragment TopN row caches.

Reference: cache.go (cache interface, rankCache, lruCache, nopCache). The
rank cache keeps the top-K (row → count) pairs per fragment so TopN phase 1
reads candidates without scanning; on TPU phase 1 can also run as a full
masked-popcount + top_k over the device matrix, so the cache is a host-side
accelerator for sparse/cold fragments and for src-parity of the cache-backed
PQL semantics (TopN without a filter consults the cache)."""

from __future__ import annotations

from collections import OrderedDict

DEFAULT_CACHE_SIZE = 50_000

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"


class RankCache:
    """Top-K rows by count (reference: cache.go rankCache)."""

    def __init__(self, max_size: int = DEFAULT_CACHE_SIZE):
        self.max_size = max_size
        self._counts: dict[int, int] = {}

    def add(self, row: int, count: int) -> None:
        if count <= 0:
            self._counts.pop(row, None)
            return
        self._counts[row] = count
        if len(self._counts) > self.max_size * 2:
            self._prune()

    def _prune(self) -> None:
        top = sorted(self._counts.items(), key=lambda kv: -kv[1])[: self.max_size]
        self._counts = dict(top)

    def get(self, row: int) -> int:
        return self._counts.get(row, 0)

    def top(self, n: int | None = None) -> list[tuple[int, int]]:
        """[(row, count)] sorted by count desc, then row asc."""
        pairs = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return pairs if n is None else pairs[:n]

    def rows(self) -> list[int]:
        return list(self._counts)

    def clear(self) -> None:
        self._counts.clear()


class LRUCache:
    """LRU row cache (reference: cache.go lruCache)."""

    def __init__(self, max_size: int = DEFAULT_CACHE_SIZE):
        self.max_size = max_size
        self._counts: OrderedDict[int, int] = OrderedDict()

    def add(self, row: int, count: int) -> None:
        if count <= 0:
            self._counts.pop(row, None)
            return
        self._counts[row] = count
        self._counts.move_to_end(row)
        while len(self._counts) > self.max_size:
            self._counts.popitem(last=False)

    def get(self, row: int) -> int:
        c = self._counts.get(row, 0)
        if c:
            self._counts.move_to_end(row)
        return c

    def top(self, n: int | None = None) -> list[tuple[int, int]]:
        pairs = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return pairs if n is None else pairs[:n]

    def rows(self) -> list[int]:
        return list(self._counts)

    def clear(self) -> None:
        self._counts.clear()


class NopCache:
    def __init__(self, max_size: int = 0):
        self.max_size = 0

    def add(self, row: int, count: int) -> None:
        pass

    def get(self, row: int) -> int:
        return 0

    def top(self, n: int | None = None) -> list[tuple[int, int]]:
        return []

    def rows(self) -> list[int]:
        return []

    def clear(self) -> None:
        pass


def make_cache(cache_type: str, size: int):
    if cache_type == CACHE_TYPE_RANKED:
        return RankCache(size)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(size)
    if cache_type == CACHE_TYPE_NONE:
        return NopCache()
    raise ValueError(f"unknown cache type {cache_type!r}")
