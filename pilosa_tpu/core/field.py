"""Field — a typed attribute dimension over columns.

Reference: field.go (Field, FieldOptions, bsiGroup; constants
bsiExistsBit=0, bsiSignBit=1, bsiOffsetBit=2). Field types:

- ``set``   — multi-value bitmap rows (default)
- ``mutex`` — single-value: setting a row clears the column's other rows
- ``bool``  — mutex with exactly rows 0 (false) / 1 (true)
- ``time``  — set + per-quantum bucket views for time-bounded reads
- ``int``   — BSI sign-magnitude bit slices in a "bsi" view
  (row 0 exists, row 1 sign, rows 2.. magnitude LSB-first — the layout
  ``pilosa_tpu.ops.bsi`` kernels consume directly)
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import asdict, dataclass
from datetime import datetime

import numpy as np

from pilosa_tpu.core import timequantum
from pilosa_tpu.core.attrstore import AttrStore
from pilosa_tpu.core.cache import DEFAULT_CACHE_SIZE
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.core.view import VIEW_BSI, VIEW_STANDARD, View
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils import durable


def _shard_slices(cols: np.ndarray):
    """Yield (shard, index_array) per touched shard via one stable
    grouping pass — per-shard boolean masks are O(n × shards) and
    dominate imports that span many shards. Shard ids are small ints,
    so the native counting argsort (O(n + shards)) replaces the
    comparison sort when available."""
    from pilosa_tpu import native

    shards = cols // np.uint64(SHARD_WIDTH)
    order = native.counting_argsort(shards)
    uniq, starts = native.uniq_sorted(shards[order])
    bounds = np.append(starts, order.size)
    for i, shard in enumerate(uniq.tolist()):
        yield int(shard), order[bounds[i] : bounds[i + 1]]

FIELD_SET = "set"
FIELD_MUTEX = "mutex"
FIELD_BOOL = "bool"
FIELD_TIME = "time"
FIELD_INT = "int"

BSI_EXISTS = 0
BSI_SIGN = 1
BSI_OFFSET = 2


@dataclass
class FieldOptions:
    field_type: str = FIELD_SET
    cache_type: str = "ranked"
    cache_size: int = DEFAULT_CACHE_SIZE
    time_quantum: str = ""
    keys: bool = False
    min: int = 0
    max: int = 0
    # True when min/max were EXPLICITLY provided: a field declared with
    # range [0, 0] (only value 0 legal) must enforce it — overloading the
    # 0/0 default as "unbounded" silently accepted any value (ADVICE r3)
    has_range: bool = False
    no_standard_view: bool = False

    def __post_init__(self) -> None:
        # a nonzero range was always enforced (and pre-has_range on-disk
        # metas must stay enforced after upgrade); only the explicit
        # [0, 0] declaration needs has_range=True from the caller
        if self.min != 0 or self.max != 0:
            self.has_range = True

    def validate(self) -> None:
        if self.field_type not in (
            FIELD_SET,
            FIELD_MUTEX,
            FIELD_BOOL,
            FIELD_TIME,
            FIELD_INT,
        ):
            raise ValueError(f"invalid field type {self.field_type!r}")
        if self.field_type == FIELD_TIME:
            timequantum.validate_quantum(self.time_quantum)
        if self.field_type == FIELD_INT and self.min > self.max:
            raise ValueError("int field: min > max")


class Field:
    def __init__(self, index: str, name: str, path: str | None, options: FieldOptions):
        options.validate()
        self.index = index
        self.name = name
        self.path = path  # <index-path>/<field-name>
        self.options = options
        self.views: dict[str, View] = {}
        self._create_lock = threading.Lock()
        self._meta_lock = threading.Lock()
        # background compaction queue, inherited by views/fragments
        # created under this field (injected by the holder chain)
        self.compactor = None
        # row attributes (reference: field.go rowAttrStore) and row-key
        # translation (reference: translate.go)
        self.row_attrs = AttrStore(
            os.path.join(path, ".row_attrs.json") if path else None
        )
        self.row_attrs.open()
        self.row_keys = TranslateStore(
            os.path.join(path, ".rowkeys.jsonl") if path else None
        )
        self.row_keys.open()
        # BSI magnitude bit depth (grows to fit the widest stored value)
        self._bit_depth = max(
            abs(int(options.min)).bit_length(), abs(int(options.max)).bit_length(), 1
        )

    # -------------------------------------------------------------- meta
    def save_meta(self) -> None:
        if self.path is None:
            return
        # serialized: concurrent per-shard import slices can grow
        # bit_depth simultaneously, and two atomic writes to the same
        # path would race on the shared tmp name (one renames it away,
        # the other's rename fails)
        with self._meta_lock:
            os.makedirs(self.path, exist_ok=True)
            meta = {
                "options": asdict(self.options),
                "bit_depth": self._bit_depth,
            }
            durable.atomic_write_file(
                os.path.join(self.path, ".meta.json"), json.dumps(meta)
            )

    @classmethod
    def load(
        cls, index: str, name: str, path: str, compactor=None, pool=None
    ) -> "Field":
        """Load a field's views and fragments from disk. With ``pool``
        (a ThreadPoolExecutor lent by Holder.open), fragment opens —
        the snapshot deserialize + ops-log replay that dominates cold
        start — are submitted concurrently; ``pool.futures`` collects
        them for the holder-level join. create_fragment_if_not_exists
        double-checks under a per-shard lock, so concurrent opens of
        different shards genuinely overlap (a view-wide lock here would
        serialize the whole load)."""
        with open(os.path.join(path, ".meta.json")) as f:
            meta = json.load(f)
        f_obj = cls(index, name, path, FieldOptions(**meta["options"]))
        f_obj._bit_depth = meta.get("bit_depth", f_obj._bit_depth)
        f_obj.compactor = compactor
        views_dir = os.path.join(path, "views")
        if os.path.isdir(views_dir):
            for view_name in sorted(os.listdir(views_dir)):
                view = f_obj.create_view_if_not_exists(view_name)
                frags_dir = os.path.join(views_dir, view_name, "fragments")
                if os.path.isdir(frags_dir):
                    for shard_name in sorted(os.listdir(frags_dir)):
                        if shard_name.isdigit() and not shard_name.endswith(".snapshotting"):
                            if pool is not None:
                                pool.futures.append(
                                    pool.submit(
                                        view.create_fragment_if_not_exists,
                                        int(shard_name),
                                    )
                                )
                            else:
                                view.create_fragment_if_not_exists(int(shard_name))
        return f_obj

    # ------------------------------------------------------------- views
    def view(self, name: str) -> View | None:
        return self.views.get(name)

    def create_view_if_not_exists(self, name: str) -> View:
        v = self.views.get(name)
        if v is not None:
            return v
        with self._create_lock:
            return self._create_view_locked(name)

    def _create_view_locked(self, name: str) -> View:
        v = self.views.get(name)
        if v is None:
            view_path = os.path.join(self.path, "views", name) if self.path else None
            # BSI views never serve TopN; skip rank-cache maintenance there
            cache_type = "none" if name == VIEW_BSI else self.options.cache_type
            v = View(
                name,
                self.index,
                self.name,
                view_path,
                cache_type,
                self.options.cache_size,
            )
            v.compactor = self.compactor
            self.views[name] = v
        return v

    def available_shards(self) -> set[int]:
        shards: set[int] = set()
        for v in self.views.values():
            shards |= v.available_shards()
        return shards

    @property
    def bit_depth(self) -> int:
        return self._bit_depth

    def time_bounds(self) -> tuple[datetime, datetime] | None:
        """[min, max) datetime range covered by materialized time views —
        bounds open-ended Row(from=/to=) queries to real data instead of
        enumerating calendar buckets from year 1."""
        lo: datetime | None = None
        hi: datetime | None = None
        for name in self.views:
            bucket = timequantum.parse_view_bucket(name, VIEW_STANDARD)
            if bucket is None:
                continue
            start, end = bucket
            lo = start if lo is None or start < lo else lo
            hi = end if hi is None or end > hi else hi
        if lo is None or hi is None:
            return None
        return lo, hi

    def close(self) -> None:
        for v in self.views.values():
            v.close()
        self.row_attrs.close()
        self.row_keys.close()

    # --------------------------------------------------------- set paths
    def _writable_views(self, timestamp: datetime | None) -> list[str]:
        if self.options.field_type == FIELD_TIME:
            names = []
            if not self.options.no_standard_view:
                names.append(VIEW_STANDARD)
            if timestamp is not None:
                names.extend(
                    timequantum.views_by_time(
                        VIEW_STANDARD, timestamp, self.options.time_quantum
                    )
                )
            return names
        return [VIEW_STANDARD]

    def set_bit(self, row: int, col: int, timestamp: datetime | None = None) -> bool:
        if self.options.field_type == FIELD_INT:
            raise ValueError("cannot set bits on an int field; use set_value")
        if self.options.field_type == FIELD_BOOL and row not in (0, 1):
            raise ValueError("bool field rows must be 0 or 1")
        shard = col // SHARD_WIDTH
        changed = False
        for view_name in self._writable_views(timestamp):
            frag = self.create_view_if_not_exists(view_name).create_fragment_if_not_exists(shard)
            if self.options.field_type in (FIELD_MUTEX, FIELD_BOOL) and view_name == VIEW_STANDARD:
                for other in frag.rows_containing(col):
                    if other != row:
                        frag.clear_bit(other, col)
            changed |= frag.set_bit(row, col)
        return changed

    def clear_bit(self, row: int, col: int) -> bool:
        shard = col // SHARD_WIDTH
        changed = False
        for view in self.views.values():
            frag = view.fragment(shard)
            if frag is not None:
                changed |= frag.clear_bit(row, col)
        return changed

    # ---------------------------------------------------------- BSI path
    def _grow_depth(self, needed: int) -> None:
        if needed > self._bit_depth:
            self._bit_depth = needed
            self.save_meta()

    def _check_range(self, lo: int, hi: int) -> None:
        """Reject values outside the declared [min, max] (reference:
        field.go importValue "value out of range"). Fields created
        without an explicit range are unbounded — depth grows with the
        data instead."""
        o = self.options
        if not o.has_range:
            return
        if lo < o.min or hi > o.max:
            bad = lo if lo < o.min else hi
            raise ValueError(
                f"field {self.name!r}: value {bad} out of range "
                f"[{o.min}, {o.max}]"
            )

    def set_value(self, col: int, value: int) -> bool:
        """Store an integer (sign-magnitude BSI write). Overwrites any
        existing value for the column."""
        if self.options.field_type != FIELD_INT:
            raise ValueError(f"field {self.name!r} is not an int field")
        value = int(value)
        self._check_range(value, value)
        self._grow_depth(abs(value).bit_length())
        shard = col // SHARD_WIDTH
        frag = self.create_view_if_not_exists(VIEW_BSI).create_fragment_if_not_exists(shard)
        changed = frag.set_bit(BSI_EXISTS, col)
        if value < 0:
            changed |= frag.set_bit(BSI_SIGN, col)
        else:
            changed |= frag.clear_bit(BSI_SIGN, col)
        mag = abs(value)
        for k in range(self._bit_depth):
            if (mag >> k) & 1:
                changed |= frag.set_bit(BSI_OFFSET + k, col)
            else:
                changed |= frag.clear_bit(BSI_OFFSET + k, col)
        return changed

    def value(self, col: int) -> tuple[int, bool]:
        """(value, exists) for a column."""
        if self.options.field_type != FIELD_INT:
            raise ValueError(f"field {self.name!r} is not an int field")
        view = self.view(VIEW_BSI)
        frag = view.fragment(col // SHARD_WIDTH) if view else None
        if frag is None or not frag.contains(BSI_EXISTS, col):
            return 0, False
        mag = 0
        for k in range(self._bit_depth):
            if frag.contains(BSI_OFFSET + k, col):
                mag |= 1 << k
        return (-mag if frag.contains(BSI_SIGN, col) else mag), True

    def clear_value(self, col: int) -> bool:
        view = self.view(VIEW_BSI)
        frag = view.fragment(col // SHARD_WIDTH) if view else None
        if frag is None:
            return False
        changed = frag.clear_bit(BSI_EXISTS, col)
        frag.clear_bit(BSI_SIGN, col)
        for k in range(self._bit_depth):
            frag.clear_bit(BSI_OFFSET + k, col)
        return changed

    def clear_values(self, cols: np.ndarray) -> None:
        """Batched BSI clear for the given columns (ImportValueRequest
        with clear=true): drops existence, sign, and every magnitude
        slice, grouped by shard."""
        if self.options.field_type != FIELD_INT:
            raise ValueError(f"field {self.name!r} is not an int field")
        cols = np.asarray(cols, dtype=np.uint64)
        view = self.view(VIEW_BSI)
        if cols.size == 0 or view is None:
            return
        shards = cols // np.uint64(SHARD_WIDTH)
        all_rows = [BSI_EXISTS, BSI_SIGN] + [
            BSI_OFFSET + k for k in range(self._bit_depth)
        ]
        for shard in np.unique(shards).tolist():
            frag = view.fragment(int(shard))
            if frag is None:
                continue
            c = cols[shards == shard]
            for row in all_rows:
                frag.bulk_import(
                    np.full(c.size, row, dtype=np.uint64), c, clear=True
                )

    # ------------------------------------------------------ bulk imports
    def import_bulk(
        self,
        rows: np.ndarray,
        cols: np.ndarray,
        timestamps: list[datetime | None] | None = None,
        clear: bool = False,
    ) -> None:
        """Batched bit import grouped by shard (reference: field.Import →
        fragment.bulkImport). ``timestamps`` routes time-field writes into
        bucket views as well."""
        rows = np.asarray(rows, dtype=np.uint64)
        cols = np.asarray(cols, dtype=np.uint64)
        if self.options.field_type in (FIELD_MUTEX, FIELD_BOOL):
            if rows.size == 0:
                return
            if self.options.field_type == FIELD_BOOL and not np.isin(
                rows, (0, 1)
            ).all():
                raise ValueError("bool field rows must be 0 or 1")
            if clear:
                # clearing needs no single-value enforcement — plain batch
                for shard, sl in _shard_slices(cols):
                    frag = self.create_view_if_not_exists(
                        VIEW_STANDARD
                    ).create_fragment_if_not_exists(shard)
                    frag.bulk_import(rows[sl], cols[sl], clear=True)
                return
            # last-wins per column, then one vectorized mutex pass per shard
            _, last = np.unique(cols[::-1], return_index=True)
            keep = np.sort(cols.size - 1 - last)
            rows, cols = rows[keep], cols[keep]
            for shard, sl in _shard_slices(cols):
                frag = self.create_view_if_not_exists(
                    VIEW_STANDARD
                ).create_fragment_if_not_exists(shard)
                frag.mutex_import(rows[sl], cols[sl])
            return
        for shard, sl in _shard_slices(cols):
            if timestamps is None or self.options.field_type != FIELD_TIME:
                views = self._writable_views(None)
                for view_name in views:
                    frag = self.create_view_if_not_exists(view_name).create_fragment_if_not_exists(shard)
                    frag.bulk_import(rows[sl], cols[sl], clear=clear)
            else:
                by_view: dict[str, list[int]] = {}
                for i in sl.tolist():
                    for view_name in self._writable_views(timestamps[i]):
                        by_view.setdefault(view_name, []).append(i)
                for view_name, ids in by_view.items():
                    frag = self.create_view_if_not_exists(view_name).create_fragment_if_not_exists(shard)
                    frag.bulk_import(rows[ids], cols[ids], clear=clear)

    def import_values(self, cols: np.ndarray, values: np.ndarray) -> None:
        """Batched BSI import (reference: field.importValue). Vectorized
        per bit-slice: one add_many/remove_many pair per slice per shard
        (overwrite semantics — old magnitude bits are cleared)."""
        if self.options.field_type != FIELD_INT:
            raise ValueError(f"field {self.name!r} is not an int field")
        cols = np.asarray(cols, dtype=np.uint64)
        values = np.asarray(values, dtype=np.int64)
        if cols.size == 0:
            return
        self._check_range(int(values.min()), int(values.max()))
        self._grow_depth(int(np.abs(values).max()).bit_length())
        shards = cols // np.uint64(SHARD_WIDTH)
        for shard in np.unique(shards).tolist():
            m = shards == shard
            c, v = cols[m], values[m]
            frag = self.create_view_if_not_exists(VIEW_BSI).create_fragment_if_not_exists(int(shard))
            zeros = np.zeros(c.size, dtype=np.uint64)
            frag.bulk_import(zeros + BSI_EXISTS, c)
            neg = v < 0
            frag.bulk_import(zeros[neg] + BSI_SIGN, c[neg])
            frag.bulk_import(zeros[~neg] + BSI_SIGN, c[~neg], clear=True)
            mags = np.abs(v).astype(np.uint64)
            for k in range(self._bit_depth):
                bit = ((mags >> np.uint64(k)) & np.uint64(1)) == 1
                row = np.uint64(BSI_OFFSET + k)
                if bit.any():
                    frag.bulk_import(zeros[bit] + row, c[bit])
                if (~bit).any():
                    frag.bulk_import(zeros[~bit] + row, c[~bit], clear=True)
