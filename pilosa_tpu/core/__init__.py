"""L1 storage & data model: Holder → Index → Field → View → Fragment.

Reference: holder.go, index.go, field.go, view.go, fragment.go, cache.go.
"""

from pilosa_tpu.core.cache import LRUCache, NopCache, RankCache, make_cache
from pilosa_tpu.core.field import (
    BSI_EXISTS,
    BSI_OFFSET,
    BSI_SIGN,
    FIELD_BOOL,
    FIELD_INT,
    FIELD_MUTEX,
    FIELD_SET,
    FIELD_TIME,
    Field,
    FieldOptions,
)
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import EXISTENCE_FIELD, Index, IndexOptions
from pilosa_tpu.core.view import VIEW_BSI, VIEW_STANDARD, View

__all__ = [
    "Holder",
    "Index",
    "IndexOptions",
    "Field",
    "FieldOptions",
    "Fragment",
    "View",
    "RankCache",
    "LRUCache",
    "NopCache",
    "make_cache",
    "VIEW_STANDARD",
    "VIEW_BSI",
    "EXISTENCE_FIELD",
    "FIELD_SET",
    "FIELD_MUTEX",
    "FIELD_BOOL",
    "FIELD_TIME",
    "FIELD_INT",
    "BSI_EXISTS",
    "BSI_SIGN",
    "BSI_OFFSET",
]
