"""Holder — the node-local root of all data.

Reference: holder.go (Holder, Open — walks the data dir loading every
index/field/view/fragment). Directory layout:

    <data-dir>/<index>/.meta.json
    <data-dir>/<index>/<field>/.meta.json
    <data-dir>/<index>/<field>/views/<view>/fragments/<shard>

Durability (docs/durability.md): the holder owns the node's ONE
background compaction queue (core/compact.py) — every fragment created
under it inherits the compactor, so an over-threshold ops log folds off
the write path. ``open()`` loads fragments through a bounded thread
pool: cold start is dominated by snapshot deserialize + ops-log replay,
which parallelize cleanly (per-fragment state, no shared mutation), and
the device upload stays lazy (first query per stack), so
restart-to-serving is bounded by the slowest fragment, not the sum.
"""

from __future__ import annotations

import os
import shutil
from concurrent.futures import ThreadPoolExecutor

from pilosa_tpu.core.compact import Compactor
from pilosa_tpu.core.index import Index, IndexOptions
from pilosa_tpu.utils import sanitize, saturation


class _LoadPool(ThreadPoolExecutor):
    """ThreadPoolExecutor plus a futures list the field loaders append
    to, so Holder.open can join (and surface the first error from)
    every concurrent fragment open."""

    def __init__(self, workers: int):
        super().__init__(max_workers=workers, thread_name_prefix="holder-load")
        self.futures: list = []


class Holder:
    def __init__(
        self,
        path: str | None = None,
        compaction_workers: int = 1,
        load_workers: int = 8,
        load_min_fragments: int = 32,
        stats=None,
    ):
        self.path = path
        self.indexes: dict[str, Index] = {}
        # contention-counted (docs/profiling.md): /debug/saturation's
        # "holder" lock family
        self._create_lock = sanitize.make_lock(
            "Holder._create_lock", inner=saturation.ContendedLock("holder")
        )
        # parallel cold-start fragment loading; <=1 loads serially
        self.load_workers = load_workers
        # fragment-count floor below which open() loads serially even
        # with workers configured: at small counts the pool's thread
        # spin-up + future machinery COSTS more than it overlaps
        # (BENCH_INGEST_r08 measured parallel 0.159s vs serial 0.066s
        # over 12 fragments)
        self.load_min_fragments = load_min_fragments
        self.compactor = Compactor(workers=compaction_workers, stats=stats)

    def _count_fragment_files(self) -> int:
        """Cheap pre-scan of on-disk fragment files (one listdir pass
        per directory — no file opens) sizing the parallel-load
        decision; tmp/quarantine leftovers (dotted suffixes) excluded."""
        count = 0
        for root, dirs, files in os.walk(self.path):
            if os.path.basename(root) == "fragments":
                count += sum(1 for fn in files if "." not in fn)
                dirs.clear()  # fragment dirs hold no nested data dirs
        return count

    def open(self) -> None:
        if self.path is None:
            return
        os.makedirs(self.path, exist_ok=True)
        use_pool = (
            self.load_workers > 1
            and self._count_fragment_files() >= self.load_min_fragments
        )
        pool = _LoadPool(self.load_workers) if use_pool else None
        try:
            for entry in sorted(os.listdir(self.path)):
                index_path = os.path.join(self.path, entry)
                if os.path.isdir(index_path) and os.path.exists(
                    os.path.join(index_path, ".meta.json")
                ):
                    self.indexes[entry] = Index.load(
                        entry, index_path, compactor=self.compactor, pool=pool
                    )
            if pool is not None:
                # join every concurrent fragment open; re-raise the first
                # failure (a quarantined snapshot logs and recovers, so
                # what reaches here is a real I/O error worth dying on)
                for fut in pool.futures:
                    fut.result()
        finally:
            if pool is not None:
                pool.shutdown(wait=True)

    def close(self) -> None:
        # drain queued compactions first: shutdown must not abandon an
        # over-threshold ops log a queued fold was about to shrink
        self.compactor.close(drain=True)
        for idx in self.indexes.values():
            idx.close()

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def create_index(self, name: str, options: IndexOptions | None = None) -> Index:
        if name in self.indexes:
            raise ValueError(f"index {name!r} already exists")
        return self.create_index_if_not_exists(name, options)

    def create_index_if_not_exists(
        self, name: str, options: IndexOptions | None = None
    ) -> Index:
        existing = self.indexes.get(name)
        if existing is not None:
            return existing
        with self._create_lock:
            return self._create_index_locked(name, options)

    def _create_index_locked(
        self, name: str, options: IndexOptions | None = None
    ) -> Index:
        existing = self.indexes.get(name)
        if existing is not None:
            return existing
        index_path = os.path.join(self.path, name) if self.path else None
        idx = Index(name, index_path, options)
        idx.compactor = self.compactor
        idx.save_meta()
        self.indexes[name] = idx
        return idx

    def delete_index(self, name: str) -> None:
        idx = self.indexes.pop(name, None)
        if idx is None:
            raise KeyError(f"index {name!r} not found")
        idx.close()
        if idx.path and os.path.isdir(idx.path):
            shutil.rmtree(idx.path)

    def wal_ledger(self) -> dict:
        """Aggregate ops-log (WAL) debt across every open fragment — the
        byte half of the /debug/resources durability row.  ``opsLogBytes``
        is what a crash would replay; ``maxOpLogFill`` is the fullest
        fragment's op_n/max_op_n fraction (1.0 = a fold is due)."""
        ops_bytes = 0
        pending_ops = 0
        fragments = 0
        worst_fill = 0.0
        for idx in list(self.indexes.values()):
            for field in list(idx.fields.values()):
                for view in list(field.views.values()):
                    for frag in list(view.fragments.values()):
                        fragments += 1
                        ops_bytes += frag.ops_bytes
                        pending_ops += frag.op_n
                        worst_fill = max(
                            worst_fill, frag.op_n / max(1, frag.max_op_n)
                        )
        return {
            "fragments": fragments,
            "opsLogBytes": ops_bytes,
            "pendingOps": pending_ops,
            "maxOpLogFill": round(worst_fill, 4),
        }

    def schema(self) -> list[dict]:
        """Schema description (reference: api.Schema)."""
        out = []
        for iname in sorted(self.indexes):
            idx = self.indexes[iname]
            fields = []
            for fname in sorted(idx.fields):
                f = idx.fields[fname]
                if fname.startswith("_"):
                    continue
                fields.append(
                    {
                        "name": fname,
                        "options": {
                            "type": f.options.field_type,
                            "cacheType": f.options.cache_type,
                            "cacheSize": f.options.cache_size,
                            "timeQuantum": f.options.time_quantum,
                            "keys": f.options.keys,
                            "min": f.options.min,
                            "max": f.options.max,
                            "hasRange": f.options.has_range,
                        },
                        "shards": sorted(f.available_shards()),
                    }
                )
            out.append(
                {
                    "name": iname,
                    "options": {
                        "keys": idx.options.keys,
                        "trackExistence": idx.options.track_existence,
                    },
                    "fields": fields,
                    "shards": sorted(idx.available_shards()),
                }
            )
        return out
