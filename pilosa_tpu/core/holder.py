"""Holder — the node-local root of all data.

Reference: holder.go (Holder, Open — walks the data dir loading every
index/field/view/fragment). Directory layout:

    <data-dir>/<index>/.meta.json
    <data-dir>/<index>/<field>/.meta.json
    <data-dir>/<index>/<field>/views/<view>/fragments/<shard>
"""

from __future__ import annotations

import os
import threading
import shutil

from pilosa_tpu.core.index import Index, IndexOptions


class Holder:
    def __init__(self, path: str | None = None):
        self.path = path
        self.indexes: dict[str, Index] = {}
        self._create_lock = threading.Lock()

    def open(self) -> None:
        if self.path is None:
            return
        os.makedirs(self.path, exist_ok=True)
        for entry in sorted(os.listdir(self.path)):
            index_path = os.path.join(self.path, entry)
            if os.path.isdir(index_path) and os.path.exists(
                os.path.join(index_path, ".meta.json")
            ):
                self.indexes[entry] = Index.load(entry, index_path)

    def close(self) -> None:
        for idx in self.indexes.values():
            idx.close()

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def create_index(self, name: str, options: IndexOptions | None = None) -> Index:
        if name in self.indexes:
            raise ValueError(f"index {name!r} already exists")
        return self.create_index_if_not_exists(name, options)

    def create_index_if_not_exists(
        self, name: str, options: IndexOptions | None = None
    ) -> Index:
        existing = self.indexes.get(name)
        if existing is not None:
            return existing
        with self._create_lock:
            return self._create_index_locked(name, options)

    def _create_index_locked(
        self, name: str, options: IndexOptions | None = None
    ) -> Index:
        existing = self.indexes.get(name)
        if existing is not None:
            return existing
        index_path = os.path.join(self.path, name) if self.path else None
        idx = Index(name, index_path, options)
        idx.save_meta()
        self.indexes[name] = idx
        return idx

    def delete_index(self, name: str) -> None:
        idx = self.indexes.pop(name, None)
        if idx is None:
            raise KeyError(f"index {name!r} not found")
        idx.close()
        if idx.path and os.path.isdir(idx.path):
            shutil.rmtree(idx.path)

    def schema(self) -> list[dict]:
        """Schema description (reference: api.Schema)."""
        out = []
        for iname in sorted(self.indexes):
            idx = self.indexes[iname]
            fields = []
            for fname in sorted(idx.fields):
                f = idx.fields[fname]
                if fname.startswith("_"):
                    continue
                fields.append(
                    {
                        "name": fname,
                        "options": {
                            "type": f.options.field_type,
                            "cacheType": f.options.cache_type,
                            "cacheSize": f.options.cache_size,
                            "timeQuantum": f.options.time_quantum,
                            "keys": f.options.keys,
                            "min": f.options.min,
                            "max": f.options.max,
                            "hasRange": f.options.has_range,
                        },
                        "shards": sorted(f.available_shards()),
                    }
                )
            out.append(
                {
                    "name": iname,
                    "options": {
                        "keys": idx.options.keys,
                        "trackExistence": idx.options.track_existence,
                    },
                    "fields": fields,
                    "shards": sorted(idx.available_shards()),
                }
            )
        return out
