"""View — groups the fragments of one variant of a field.

Reference: view.go (view, viewStandard, time-view naming). A set field has
one "standard" view; a time field adds one view per calendar bucket; an int
(BSI) field keeps its bit-slice rows in a "bsi" view.
"""

from __future__ import annotations

import itertools
import os
import threading

from pilosa_tpu.core.fragment import Fragment

VIEW_STANDARD = "standard"
VIEW_BSI = "bsi"

_VIEW_STAMPS = itertools.count(1)


class View:
    def __init__(
        self,
        name: str,
        index: str,
        field: str,
        path: str | None,
        cache_type: str,
        cache_size: int,
    ):
        self.name = name
        self.index = index
        self.field = field
        self.path = path  # <field-path>/views/<name>
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.fragments: dict[int, Fragment] = {}
        self._create_lock = threading.Lock()
        # per-shard creation locks: fragment OPEN (snapshot deserialize +
        # ops-log replay, the cold-start cost) must run outside any
        # view-wide lock or holder-load-workers degenerates to a serial
        # load; _create_lock only guards this dict and self.fragments
        self._open_locks: dict[int, threading.Lock] = {}
        # background compaction queue (core/compact.py) injected by the
        # holder chain; every fragment created here inherits it so an
        # over-threshold ops log folds off the write path
        self.compactor = None
        # mutation stamp covering EVERY fragment of this view (bumped on
        # any fragment mutation or creation): lets the query compiler's
        # stack cache validate a whole shard list in O(1) instead of
        # reading every fragment's version per query. Stamps come from a
        # GLOBAL counter so a deleted-and-recreated view can never replay
        # a stamp an old cache entry carries.
        self.version = next(_VIEW_STAMPS)

    def _bump_version(self) -> None:
        self.version = next(_VIEW_STAMPS)

    def fragment(self, shard: int) -> Fragment | None:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        # double-checked under a PER-SHARD lock: two concurrent writers
        # racing the same shard would otherwise build two Fragment
        # objects over the same file (clashing snapshot tmp files, lost
        # updates) — while opens of DIFFERENT shards (the parallel
        # holder cold start) proceed concurrently. The fragment is
        # published only after open() completes, so readers never see a
        # half-loaded bitmap.
        frag = self.fragments.get(shard)
        if frag is not None:
            return frag
        with self._create_lock:
            frag = self.fragments.get(shard)
            if frag is not None:
                return frag
            shard_lock = self._open_locks.setdefault(shard, threading.Lock())
        with shard_lock:
            frag = self.fragments.get(shard)
            if frag is not None:
                return frag
            frag_path = (
                os.path.join(self.path, "fragments", str(shard))
                if self.path
                else None
            )
            frag = Fragment(
                frag_path,
                self.index,
                self.field,
                self.name,
                shard,
                cache_type=self.cache_type,
                cache_size=self.cache_size,
            )
            frag._compactor = self.compactor
            frag.open()
            frag._on_mutate = self._bump_version
            self.fragments[shard] = frag
            self._bump_version()
        return frag

    def available_shards(self) -> set[int]:
        return set(self.fragments)

    def remove_fragment(self, shard: int) -> bool:
        """Drop a fragment and its on-disk file — the relinquish half of a
        cluster resize handoff (reference: fragment deletion in
        ResizeJob). Bumps the view version so device stack caches built
        over the old shard set invalidate."""
        frag = self.fragments.pop(shard, None)
        if frag is None:
            return False
        self._bump_version()
        frag.close()
        # drop() marks the fragment relinquished under its own lock —
        # a compaction already queued (or mid-flight) for it must not
        # rewrite the file and resurrect the shard's data on disk
        frag.drop()
        return True

    def close(self) -> None:
        for frag in self.fragments.values():
            frag.close()
