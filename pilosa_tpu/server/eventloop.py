"""Event-driven HTTP front end: asyncio accept/read/write loop.

Replaces the thread-per-request ``ThreadingHTTPServer`` stack that
plateaued at c32 (BENCH_SWEEP_r06_cpu: sync_count_qps_c32 = 0.88x c1 —
parked OS threads + a connect-storm-sized accept backlog).  Design
(docs/serving.md):

- ONE event-loop thread owns all socket I/O: accept, HTTP/1.1 head/body
  reads with keep-alive multiplexing, slow-client timeouts, and response
  writes.  Ten thousand idle connections cost ten thousand coroutines,
  not ten thousand OS threads.
- Admission control between read and execution: per-class (query /
  write / control) concurrency limits with bounded wait queues.  A full
  queue answers 429 + Retry-After immediately — load sheds at the door
  instead of stacking invisible thread queues (the PR 4
  ``request_queue_size = 128`` band-aid this replaces).
- Execution stays on a BOUNDED worker pool: the parsed request is handed
  to a worker thread that runs the existing ``Handler`` route logic over
  in-memory files, so concurrent sync queries still meet in the
  WaveScheduler and coalesce into shared device readback waves — the
  pool turns over at wave cadence while excess requests wait in
  admission, not on parked threads.
- The per-query deadline (X-Pilosa-Deadline-Ms / query-timeout-ms)
  starts when the request head arrives: a query that exhausts its budget
  while queued gets the labeled 504 and never executes.

The event loop itself must never block: no socket/file I/O, no
``time.sleep``, no thread spawns inside coroutines — the ``asyncpurity``
analyzer rule enforces this, with ``run_in_executor`` as the one
sanctioned hand-off to blocking code.
"""

from __future__ import annotations

import array
import asyncio
import io
import os
import re
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from pilosa_tpu import __version__
from pilosa_tpu.parallel import resilience
from pilosa_tpu.server.http import Handler, _ServerCore
from pilosa_tpu.utils import StatsClient, sanitize

# combined request-line + headers byte cap (http.server's _MAXLINE era
# limit); past it the client gets 431 and the connection closes
MAX_HEADER_BYTES = 65536
# asyncio stream high-water: sized so a multi-MiB import-roaring body
# buffers in few loop wakeups instead of 64 KiB dribbles (see the
# start_server call); per-connection memory stays bounded at 2x this
STREAM_BUFFER_BYTES = 1 << 20

# listen backlog: the kernel absorbs a connect burst while the loop
# accepts; admission control (not the backlog) is the real limiter, so
# this needs no per-deployment knob — the PR 4 request_queue_size=128
# band-aid is gone
LISTEN_BACKLOG = 1024

_CLASS_QUERY = "query"
_CLASS_WRITE = "write"
_CLASS_CONTROL = "control"


def route_class(method: str, path: str) -> str:
    """Admission class of a request path: queries (public + internal
    fan-out legs), writes (imports), control (everything else — status,
    schema, metrics, debug).  Control is deliberately its own small
    lane: a query flood must not starve /status heartbeats, or the
    cluster would dead-mark a node that is merely busy."""
    p = path.split("?", 1)[0]
    if p.endswith("/query") and p.startswith("/index/"):
        return _CLASS_QUERY
    if p.startswith("/internal/query"):
        return _CLASS_QUERY
    if "/import" in p:
        return _CLASS_WRITE
    return _CLASS_CONTROL


class _Abort(Exception):
    """Terminate a connection with one final error response."""

    def __init__(self, code: int, reason: str, message: str,
                 retry_after: str | None = None):
        super().__init__(message)
        self.code = code
        self.reason = reason  # queries_rejected{reason=} tag value
        self.message = message
        self.retry_after = retry_after


class _ConnState:
    """Per-connection watchdog state for the timeout sweeper.

    Slow-client cuts (keep-alive idle reap, slowloris head/body
    timeouts) are enforced by ONE periodic sweeper task over these
    records instead of a ``wait_for`` wrapper per read — three timer
    handles per request is measurable overhead on the c1 hot path, and
    DoS cuts don't need precision timing."""

    __slots__ = ("writer", "phase", "since", "aborted", "readahead")

    IDLE = 0  # between requests (keep-alive)
    HEAD = 1  # reading request line + headers
    BODY = 2  # reading the body
    BUSY = 3  # dispatched / writing the response (deadline governs)

    def __init__(self, writer):
        self.writer = writer
        # a connection that has sent NOTHING yet gets the idle grace
        # (held-open connection pools are the normal case — the 10k
        # smoke test holds exactly these); the slowloris window starts
        # at the first byte of a request head
        self.phase = _ConnState.IDLE
        self.since = time.monotonic()
        self.aborted = False
        # bytes read past a head's CRLFCRLF (pipelined body prefix /
        # next request) — consumed by the body read before the socket
        self.readahead = b""

    def enter(self, phase: int) -> None:
        self.phase = phase
        self.since = time.monotonic()


class _BufferedHandler(Handler):
    """One fully-read request executed against in-memory files.

    The event loop owns the real socket; a worker thread runs this shim,
    which re-parses the raw request through ``BaseHTTPRequestHandler``
    machinery (one parser, identical semantics to the threaded path) and
    dispatches through the unchanged ``Handler`` route table.  The
    response accumulates in ``wfile`` (a BytesIO) for the loop to write
    back; ``close_connection`` reports the keep-alive decision."""

    def __init__(self, server, raw: bytes, client_address, deadline=None,
                 admission_wait: float | None = None,
                 arrival: float | None = None):
        # deliberately NOT calling super().__init__: the socketserver
        # constructor runs the blocking per-connection protocol; this
        # shim replaces exactly that part
        self.server = server
        self.client_address = client_address
        self.rfile = io.BytesIO(raw)
        self.wfile = io.BytesIO()
        # admission-time deadline: _query_context prefers this over
        # re-parsing the header so queue wait counts against the budget
        self.admission_deadline = deadline
        # measured admission-lane wait for THIS request: the profile
        # and the flight recorder attribute queue time vs query time
        # from it (docs/observability.md)
        self.admission_wait_s = admission_wait
        # monotonic instant the request HEAD started arriving: the
        # workload capture stamps records with it so replayed arrival
        # spacing reflects offered load, not settle times
        # (docs/workload.md; None on the threaded listener)
        self.arrival_monotonic = arrival
        self.close_connection = True
        self.requestline = ""
        self.request_version = ""
        self.command = ""
        self._run()

    def handle_expect_100(self) -> bool:
        # the event loop already answered the interim 100 before it read
        # the body; writing another into the buffered response would
        # prepend a stray interim status
        return True

    def _run(self) -> None:
        self.raw_requestline = self.rfile.readline(65537)
        if not self.raw_requestline:
            return
        if len(self.raw_requestline) > 65536:
            self.requestline = ""
            self.send_error(414)
            return
        if not self.parse_request():
            return  # parse_request already wrote the error response
        method = getattr(self, "do_" + self.command, None)
        if method is None:
            self.send_error(501, f"Unsupported method ({self.command!r})")
            return
        method()


class EventHTTPServer(_ServerCore):
    """HTTP front end bound to an API façade — the event-driven default.

    Same attribute surface as the legacy ``ThreadedHTTPServer``
    (``query_router`` / ``import_router`` hooks, ``extra_routes``,
    ``ssl_context``, ``serve_background``/``shutdown``/``server_close``)
    so the runtime Server and the cluster layer wire either
    interchangeably; the listener internals are an asyncio loop on one
    background thread."""

    def __init__(self, addr: tuple[str, int], api, stats: StatsClient | None = None):
        # bind in the constructor (like socketserver) so server_address
        # is final before serve_background — Server.open publishes the
        # bound port to the cluster join before the loop thread starts
        self.socket = socket.create_server(addr, backlog=LISTEN_BACKLOG)
        self.server_address = self.socket.getsockname()
        self._init_core(api, stats)
        # admission knobs (config: docs/configuration.md); Server.open
        # overwrites these from Config before serve_background
        self.max_connections = 0  # 0 = unlimited
        self.admission_queue_depth = 256  # per class; 0 = unbounded
        self.keepalive_idle_s = 75.0  # idle keep-alive reap; 0 = never
        self.request_read_timeout_s = 10.0  # slowloris head/body cut
        self.worker_threads = 0  # query-class concurrency; 0 = auto
        # write-lane backpressure tied to compaction debt (docs/
        # durability.md): when the holder's queued+in-flight compactions
        # exceed the limit, write-class requests get 429 + Retry-After —
        # unchecked ingest past compaction capacity grows every ops log
        # (and crash-replay time) without bound. 0 disables; the debt
        # callable is wired by Server.open.
        self.compaction_max_debt = 0
        self.compaction_debt = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._stop: asyncio.Event | None = None
        self._pool: ThreadPoolExecutor | None = None
        self._admission: dict[str, "_Admission"] = {}
        self._conn_tasks: set[asyncio.Task] = set()
        self._conns: set[_ConnState] = set()
        self._conn_count = 0
        self._started = threading.Event()
        self._closed = False
        # multi-process serving (docs/multiprocess.md): extra listeners
        # added AFTER boot — the SO_REUSEPORT shared public socket a
        # supervised child binds once its cluster join completes — and
        # the accept-and-pass adoption plumbing for the fallback mode.
        # ``shared_listener`` is the /debug/vars serving-snapshot
        # surface naming which sharing mode is active.
        self._extra_sockets: list[socket.socket] = []
        self._extra_servers: list[asyncio.AbstractServer] = []
        self._fd_listener: socket.socket | None = None
        self._fd_path: str | None = None
        self._fd_conns: set[socket.socket] = set()
        self.shared_listener: dict | None = None

    # ------------------------------------------------------------ lifecycle
    def serve_background(self) -> threading.Thread:
        t = threading.Thread(
            target=self._run_loop, daemon=True, name="http-eventloop"
        )
        self._thread = t
        t.start()
        # the caller may connect immediately (the listener is already
        # bound, so connects queue in the backlog) but waiting for the
        # loop avoids a read-side race in zero-delay tests
        self._started.wait(5.0)
        return t

    def shutdown(self) -> None:
        self._closed = True
        loop, stop = self._loop, self._stop
        if loop is not None and stop is not None and loop.is_running():
            loop.call_soon_threadsafe(stop.set)
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def server_close(self) -> None:
        self._closed = True
        try:
            self.socket.close()
        except OSError:
            pass
        for sock in self._extra_sockets:
            try:
                sock.close()
            except OSError:
                pass
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        # under PILOSA_TPU_SANITIZE=1 every blocking acquire of a
        # non-loop_safe lock on THIS thread becomes a finding — the
        # runtime check behind the static loop-purity rule
        sanitize.mark_loop_thread()
        try:
            loop.run_until_complete(self._serve())
        finally:
            sanitize.unmark_loop_thread()
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            finally:
                asyncio.set_event_loop(None)
                loop.close()

    def _class_limits(self) -> dict[str, int]:
        # auto query concurrency is sized to WAVE OCCUPANCY, not cores:
        # query workers spend their life parked as wave followers or in
        # GIL-released device calls, so capping them at the core count
        # starves the scheduler of wave-mates under fan-in (measured
        # here: a 2-core box with an 8-slot query lane put c32 BELOW c8
        # — the exact plateau this front end removes). Floor 32, ceiling
        # 64 (= batch-max-queries, one full wave).
        wt = self.worker_threads or max(32, min(64, (os.cpu_count() or 4) * 4))
        return {
            _CLASS_QUERY: wt,
            _CLASS_WRITE: max(2, wt // 2),
            _CLASS_CONTROL: max(4, wt // 4),
        }

    async def _serve(self) -> None:
        self._stop = asyncio.Event()
        limits = self._class_limits()
        # pool size = sum of class caps: an admission slot always implies
        # a worker thread, so acquiring the semaphore IS the queue exit
        self._pool = ThreadPoolExecutor(
            max_workers=sum(limits.values()), thread_name_prefix="http-worker"
        )
        depth = self.admission_queue_depth
        self._admission = {
            cls: _Admission(limit, depth) for cls, limit in limits.items()
        }
        loop = asyncio.get_running_loop()
        loop.set_exception_handler(self._loop_exception)
        kwargs: dict = {}
        if self.ssl_context is not None:
            kwargs["ssl"] = self.ssl_context
            # a TCP-open-no-ClientHello client must not hold a
            # handshake slot forever — same slow-client cut as the
            # plaintext head read
            kwargs["ssl_handshake_timeout"] = (
                self.request_read_timeout_s or None
            )
        server = await asyncio.start_server(
            self._handle_conn,
            sock=self.socket,
            # stream buffer sized for BULK bodies, not heads: with the
            # old 64 KiB limit a 2 MiB import-roaring frame drained in
            # ~16-32 read() wakeups, each queued behind whatever GIL
            # hold a numpy-crunching worker had in flight — measured
            # ~100ms per body under sustained ingest. Heads keep the
            # MAX_HEADER_BYTES cap via the explicit check in _read_head
            # (LimitOverrunError at this limit stays the backstop).
            limit=STREAM_BUFFER_BYTES,
            backlog=LISTEN_BACKLOG,
            **kwargs,
        )
        sweeper = asyncio.ensure_future(self._sweep_slow_clients())
        lag_probe = None
        if self.saturation is not None and self.saturation.enabled:
            lag_probe = asyncio.ensure_future(self._lag_probe())
        self._started.set()
        try:
            await self._stop.wait()
        finally:
            if lag_probe is not None:
                lag_probe.cancel()
            sweeper.cancel()
            server.close()
            await server.wait_closed()
            for extra in self._extra_servers:
                extra.close()
            if self._extra_servers:
                await asyncio.gather(
                    *(s.wait_closed() for s in self._extra_servers),
                    return_exceptions=True,
                )
            self._close_fd_plumbing(loop)
            for t in list(self._conn_tasks):
                t.cancel()
            if self._conn_tasks:
                await asyncio.gather(*self._conn_tasks, return_exceptions=True)

    # ------------------------------------------------- shared public port
    def add_shared_listener(self, host: str, port: int) -> None:
        """Bind an ADDITIONAL public (host, port) with SO_REUSEPORT and
        serve it with the same per-connection coroutine as the primary
        socket (docs/multiprocess.md).  Called by Server.open AFTER the
        cluster join completes — readiness gating: the kernel only
        balances new connections across sockets that exist, so this
        child joins the shared-port group exactly when it can serve its
        shard subset.  Thread-safe; requires the loop to be running."""
        loop = self._loop
        if loop is None or not loop.is_running():
            raise RuntimeError("add_shared_listener requires a running loop")
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            sock.bind((host, port))
            sock.listen(LISTEN_BACKLOG)
            sock.setblocking(False)
        except OSError:
            sock.close()
            raise
        fut = asyncio.run_coroutine_threadsafe(self._start_extra(sock), loop)
        fut.result(timeout=10.0)
        self._extra_sockets.append(sock)
        self.shared_listener = {
            "mode": "reuseport",
            "bind": f"{host}:{port}",
        }

    async def _start_extra(self, sock: socket.socket) -> None:
        kwargs: dict = {}
        if self.ssl_context is not None:
            kwargs["ssl"] = self.ssl_context
            kwargs["ssl_handshake_timeout"] = (
                self.request_read_timeout_s or None
            )
        server = await asyncio.start_server(
            self._handle_conn,
            sock=sock,
            limit=STREAM_BUFFER_BYTES,
            backlog=LISTEN_BACKLOG,
            **kwargs,
        )
        self._extra_servers.append(server)

    def add_fd_listener(self, path: str) -> None:
        """Adopt supervisor-passed public connections — the fallback
        when SO_REUSEPORT is unavailable (docs/multiprocess.md): listen
        on a unix socket where the accept-and-pass parent ships each
        accepted fd via SCM_RIGHTS; every delivered fd becomes an
        ordinary ``_handle_conn`` connection on this loop.  Thread-safe;
        requires the loop to be running."""
        loop = self._loop
        if loop is None or not loop.is_running():
            raise RuntimeError("add_fd_listener requires a running loop")
        try:
            os.unlink(path)
        except OSError:
            pass
        lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        lsock.bind(path)
        lsock.listen(8)
        lsock.setblocking(False)
        self._fd_listener = lsock
        self._fd_path = path
        loop.call_soon_threadsafe(
            loop.add_reader, lsock.fileno(), self._fd_accept, lsock
        )
        self.shared_listener = {"mode": "fd-pass", "bind": path}

    def _fd_accept(self, lsock: socket.socket) -> None:
        # loop-thread reader callback: non-blocking accept of a
        # supervisor control connection (one per parent, reconnected
        # after a parent restart); fds arrive on it via _fd_recv
        try:
            conn, _ = lsock.accept()
        except (BlockingIOError, InterruptedError, OSError):
            return
        conn.setblocking(False)
        self._fd_conns.add(conn)
        assert self._loop is not None
        self._loop.add_reader(conn.fileno(), self._fd_recv, conn)

    def _fd_recv(self, conn: socket.socket) -> None:
        # loop-thread reader callback: drain one SCM_RIGHTS message and
        # adopt every delivered fd as a served connection
        try:
            msg, ancdata, _flags, _addr = conn.recvmsg(
                1, socket.CMSG_LEN(16 * array.array("i").itemsize)
            )
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            msg, ancdata = b"", []
        fds: list[int] = []
        for level, ctype, data in ancdata:
            if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
                usable = len(data) - (len(data) % array.array("i").itemsize)
                fds.extend(array.array("i", data[:usable]))
        if not msg and not fds:
            # parent hung up (restarting or draining): retire the
            # control connection; a new parent reconnects on the path
            assert self._loop is not None
            self._loop.remove_reader(conn.fileno())
            self._fd_conns.discard(conn)
            conn.close()
            return
        for fd in fds:
            try:
                csock = socket.socket(fileno=fd)
                csock.setblocking(False)
            except OSError:
                try:
                    os.close(fd)
                except OSError:
                    pass
                continue
            self.stats.count("connections_adopted")
            asyncio.ensure_future(self._adopt(csock))

    async def _adopt(self, csock: socket.socket) -> None:
        """Turn one passed fd into a served connection: the stream
        protocol invokes ``_handle_conn`` exactly as the primary
        listener's accepts do (TLS handshake included when configured,
        since the parent passes the raw TCP fd)."""
        assert self._loop is not None
        try:
            reader = asyncio.StreamReader(
                limit=STREAM_BUFFER_BYTES, loop=self._loop
            )
            protocol = asyncio.StreamReaderProtocol(
                reader, self._handle_conn, loop=self._loop
            )
            kwargs: dict = {}
            if self.ssl_context is not None:
                kwargs["ssl"] = self.ssl_context
            await self._loop.connect_accepted_socket(
                lambda: protocol, csock, **kwargs
            )
        except Exception as e:  # pilosa: allow(broad-except) — one bad
            # fd must not kill the adoption path for every later one;
            # logger lock is loop_safe + bounded, exceptional by
            # construction
            self.log(f"fd adoption failed: {e!r}")  # pilosa: allow(loop-purity)
            try:
                csock.close()
            except OSError:
                pass

    def _close_fd_plumbing(self, loop) -> None:
        for conn in list(self._fd_conns):
            try:
                loop.remove_reader(conn.fileno())
                conn.close()
            except OSError:
                pass
        self._fd_conns.clear()
        if self._fd_listener is not None:
            try:
                loop.remove_reader(self._fd_listener.fileno())
                self._fd_listener.close()
            except OSError:
                pass
            self._fd_listener = None
        if self._fd_path is not None:
            try:
                os.unlink(self._fd_path)
            except OSError:
                pass
            self._fd_path = None

    async def _sweep_slow_clients(self) -> None:
        """The slow-client watchdog: one periodic pass over open
        connections enforces the keep-alive idle reap and the slowloris
        head/body timeouts.  Centralized so the per-request hot path
        carries no timer bookkeeping; granularity is a fraction of the
        smallest configured cut (DoS defenses don't need precision)."""
        cuts = [
            t for t in (self.request_read_timeout_s, self.keepalive_idle_s)
            if t and t > 0
        ]
        interval = max(0.05, min(min(cuts), 2.0) / 4) if cuts else 2.0
        while True:
            await asyncio.sleep(interval)
            now = time.monotonic()
            for conn in list(self._conns):
                try:
                    age = now - conn.since
                    if conn.phase == _ConnState.IDLE:
                        if 0 < self.keepalive_idle_s < age:
                            conn.aborted = True
                            conn.writer.close()  # silent reap: nothing owed
                    elif conn.phase in (_ConnState.HEAD, _ConnState.BODY):
                        if 0 < self.request_read_timeout_s < age:
                            reason = (
                                "header_timeout"
                                if conn.phase == _ConnState.HEAD
                                else "body_timeout"
                            )
                            self._reject(reason)
                            conn.aborted = True
                            msg = (
                                "timed out reading request head"
                                if conn.phase == _ConnState.HEAD
                                else "timed out reading request body"
                            )
                            await self._write_simple(
                                conn.writer, 408, msg, retry_after="1",
                                close=True,
                            )
                            conn.writer.close()
                except Exception:  # pilosa: allow(broad-except) — one
                    # torn-down connection must not kill the watchdog
                    # for every other connection
                    continue

    async def _lag_probe(self) -> None:
        """The event-loop saturation probe (docs/profiling.md): a
        scheduled wakeup per tick, recording how late the loop actually
        ran it — the loop's run-queue delay, which is exactly what every
        queued response write and head parse waits behind.  The same
        tick samples each admission class's in-flight/limit fraction so
        worker-pool utilization is a windowed distribution, not a
        single scrape's instantaneous guess."""
        interval = 0.1
        mon = self.saturation
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(interval)
            mon.observe_loop_lag(max(0.0, time.monotonic() - t0 - interval))
            for cls, adm in self._admission.items():
                mon.observe_worker_util(
                    cls, adm.in_flight / max(1, adm.limit)
                )

    def _loop_exception(self, loop, context) -> None:
        # an exception nothing awaited: a bug by definition (the
        # 10k-connection smoke test asserts this counter stays 0)
        self.stats.count("eventloop_unhandled_exceptions")
        self.log(f"event loop unhandled exception: {context.get('message')}"
                 f" {context.get('exception')!r}")

    # ---------------------------------------------------------- connection
    def serving_snapshot(self) -> dict:
        adm = {
            cls: {
                "limit": a.limit,
                "queueDepth": a.waiting,
                "queueCap": a.depth,
                "inFlight": a.in_flight,
            }
            for cls, a in self._admission.items()
        }
        return {
            "mode": "event",
            "connectionsOpen": self._conn_count,
            "maxConnections": self.max_connections,
            "admission": adm,
            # multi-process serving (docs/multiprocess.md): which
            # public-port sharing mode this process participates in —
            # {"mode": "reuseport"|"fd-pass", "bind": ...}, or
            # {"mode": "none"} for an ordinary solo listener
            "sharedListener": self.shared_listener or {"mode": "none"},
        }

    def _set_conn_gauge(self) -> None:
        self.stats.gauge("connections_open", float(self._conn_count))

    def _reject(self, reason: str) -> None:
        self.stats.count("queries_rejected", tags={"reason": reason})

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        conn = _ConnState(writer)
        self._conns.add(conn)
        self._conn_count += 1
        self._set_conn_gauge()
        self.stats.count("connections_accepted")
        try:
            if 0 < self.max_connections < self._conn_count:
                self._reject("max_connections")
                await self._write_simple(
                    writer, 503, "server connection limit reached",
                    retry_after="1", close=True,
                )
                return
            await self._conn_loop(reader, writer, conn)
        except asyncio.CancelledError:
            raise  # shutdown path — propagate so gather() settles
        except (ConnectionResetError, BrokenPipeError, TimeoutError):
            pass  # client tore the connection down — close quietly
        except Exception as e:  # pilosa: allow(broad-except) — the
            # per-connection chokepoint: a handler bug must kill ONE
            # connection, never the accept loop
            self.stats.count("eventloop_unhandled_exceptions")
            # error path only: one bounded line to stderr under the
            # logger lock, exceptional by construction
            self.log(f"connection handler error: {e!r}")  # pilosa: allow(loop-purity)
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            self._conns.discard(conn)
            self._conn_count -= 1
            self._set_conn_gauge()
            writer.close()

    async def _conn_loop(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter,
                         conn: _ConnState) -> None:
        assert self._stop is not None
        while not self._stop.is_set():
            try:
                head = await self._read_head(reader, conn)
            except _Abort as e:
                self._reject(e.reason)
                await self._write_simple(
                    writer, e.code, e.message,
                    retry_after=e.retry_after, close=True,
                )
                return
            if head is None:
                return  # clean close: EOF, idle reap, or slowloris cut
            # conn.since was stamped when the head's first byte arrived
            # (_read_head's enter(HEAD)) — capture it BEFORE the body
            # phase re-stamps it; this is the arrival the workload
            # capture records for replay spacing
            arrival = conn.since
            try:
                method, path, headers, head = self._parse_head(head)
                cls = route_class(method, path)
                # the budget clock starts NOW — admission-queue wait and
                # body-read time both spend it (acceptance: a query that
                # exhausts its budget while queued never executes).
                # QUERY class only: on the threaded path the deadline
                # governed query routes alone (_query_context), so an
                # import or /status probe queued past query-timeout-ms
                # must not start 504ing — a busy-but-alive node's
                # heartbeats dying at admission is the dead-marking the
                # dedicated control lane exists to prevent
                deadline = None
                if cls == _CLASS_QUERY:
                    deadline = resilience.deadline_from_header(
                        headers.get(resilience.DEADLINE_HEADER.lower())
                    )
                    if deadline is None and self.query_timeout_ms > 0:
                        deadline = resilience.Deadline(
                            self.query_timeout_ms / 1e3
                        )
                body = await self._read_body(reader, writer, headers, conn)
            except _Abort as e:
                self._reject(e.reason)
                await self._write_simple(
                    writer, e.code, e.message,
                    retry_after=e.retry_after, close=True,
                )
                return
            if body is None:
                return  # client disconnected mid-body (or slow-body cut)
            if cls == _CLASS_QUERY:
                # result-cache fast path (docs/result-cache.md): a
                # repeated read query whose mutation-stamped key is
                # cached is answered RIGHT HERE on the loop thread —
                # no admission lane, no worker-pool hop, no GIL-bound
                # re-execution.  Pure CPU (memoized parse + dict hit),
                # so the loop's no-blocking contract holds.
                served = await self._serve_cached(
                    writer, method, path, headers, body, arrival
                )
                if served is not None:
                    if not served:
                        return
                    conn.enter(_ConnState.IDLE)
                    continue
            conn.enter(_ConnState.BUSY)
            keep = await self._admit_and_dispatch(
                writer, cls, head + body, deadline, arrival
            )
            if not keep:
                return
            conn.enter(_ConnState.IDLE)

    async def _read_head(self, reader: asyncio.StreamReader,
                         conn: _ConnState) -> bytes | None:
        """Request head (request line + headers + CRLFCRLF), or None on
        clean EOF / a watchdog cut.  The idle reap and the slowloris
        timeout are enforced by the sweeper task via ``conn.phase`` —
        the reads themselves carry no timers.

        Read incrementally rather than with ``readuntil``: the stream
        limit is sized for bulk import BODIES (STREAM_BUFFER_BYTES), so
        the MAX_HEADER_BYTES cap must be enforced here, MID-STREAM — a
        header flood has to die at the cap, not once a terminator shows
        up.  Bytes past the CRLFCRLF (a pipelined body prefix) stay in
        ``conn.readahead`` for ``_read_body``."""
        pending = conn.readahead
        conn.readahead = b""
        if not pending:
            first = await reader.read(1)
            if not first:
                return None  # EOF between requests (or watchdog close)
            pending = first
        conn.enter(_ConnState.HEAD)
        buf = bytearray(pending)
        while True:
            idx = buf.find(b"\r\n\r\n")
            if idx >= 0:
                head = bytes(buf[: idx + 4])
                if len(head) > MAX_HEADER_BYTES:
                    raise _Abort(
                        431, "header_too_large",
                        f"request head exceeds {MAX_HEADER_BYTES} bytes",
                    )
                conn.readahead = bytes(buf[idx + 4 :])
                return head
            if len(buf) > MAX_HEADER_BYTES:
                raise _Abort(
                    431, "header_too_large",
                    f"request head exceeds {MAX_HEADER_BYTES} bytes",
                )
            chunk = await reader.read(65536)
            if not chunk:
                return None  # hung up mid-head, or the sweeper's 408 cut
            buf += chunk

    def _parse_head(self, head: bytes) -> tuple[str, str, dict, bytes]:
        """(method, path, lowercase-header dict, possibly-rewritten head).
        Parsing here is minimal — admission routing and framing only; the
        worker-side shim re-parses with the stdlib machinery."""
        try:
            text = head.decode("iso-8859-1")
            request_line, _, header_text = text.partition("\r\n")
            method, path, _version = request_line.split(" ", 2)
        except ValueError:
            raise _Abort(400, "bad_request", "malformed request line") from None
        headers: dict[str, str] = {}
        for line in header_text.split("\r\n"):
            if not line:
                continue
            key, sep, value = line.partition(":")
            if not sep:
                continue
            k = key.strip().lower()
            v = value.strip()
            if k == "content-length" and headers.get(k, v) != v:
                # conflicting Content-Length values: the loop would
                # frame by one while a downstream parser may honor the
                # other — the classic request-smuggling split on a
                # keep-alive connection; refuse outright
                raise _Abort(
                    400, "bad_request", "conflicting Content-Length headers"
                )
            if k == "transfer-encoding" and k in headers:
                # merge duplicates so the chunked check below sees every
                # declared coding, not just the first line's
                headers[k] += ", " + v
                continue
            headers.setdefault(k, v)
        if "chunked" in headers.get("transfer-encoding", "").lower():
            raise _Abort(
                501, "unsupported_transfer_encoding",
                "chunked request bodies are not supported; "
                "send Content-Length",
            )
        return method.upper(), path, headers, head

    async def _read_body(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter,
                         headers: dict, conn: _ConnState) -> bytes | None:
        try:
            length = int(headers.get("content-length") or 0)
        except ValueError:
            raise _Abort(400, "bad_request", "bad Content-Length") from None
        if "100-continue" in headers.get("expect", "").lower():
            # answer the interim 100 from the loop; the worker-side
            # shim's handle_expect_100 is a no-op so the buffered
            # response never carries a second interim status
            writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            await writer.drain()
        if length <= 0:
            return b""
        conn.enter(_ConnState.BODY)  # sweeper owns the slow-body cut
        pending = conn.readahead
        if pending:
            # body prefix already buffered by the incremental head read
            if len(pending) >= length:
                conn.readahead = pending[length:]
                return pending[:length]
            conn.readahead = b""
        try:
            rest = await reader.readexactly(length - len(pending))
        except asyncio.IncompleteReadError:
            if not conn.aborted:
                self.stats.count("connections_aborted_midbody")
            return None
        return pending + rest if pending else rest

    # public query path: POST /index/{name}/query, optionally with a
    # ?shards= scope — the ONLY shape the cache fast path serves; any
    # other param (explain/profile/...) or the /internal legs take the
    # worker path untouched
    _CACHE_PATH_RE = re.compile(r"^/index/([^/?]+)/query(?:\?(.*))?$")

    async def _serve_cached(self, writer, method: str, path: str,
                            headers: dict, body: bytes,
                            arrival: float | None) -> bool | None:
        """Serve a repeated read query straight from the event loop
        (docs/result-cache.md).  Returns None when the worker path must
        run, else the keep-alive verdict.  Everything here is pure CPU
        — the asyncpurity contract for loop-thread code."""
        cache = getattr(self, "result_cache", None)
        if cache is None or not cache.enabled or method != "POST":
            return None
        m = self._CACHE_PATH_RE.match(path)
        if m is None:
            return None
        index, qs = m.group(1), m.group(2) or ""
        shards = None
        if qs:
            params = dict(
                p.partition("=")[::2] for p in qs.split("&") if p
            )
            if set(params) - {"shards"}:
                return None  # explain/profile/proto knobs: worker path
            raw_shards = params.get("shards", "")
            if raw_shards:
                try:
                    shards = [
                        int(s) for s in raw_shards.split(",") if s != ""
                    ]
                except ValueError:
                    return None  # malformed scope: worker owns the 4xx
        # content negotiation: the cache holds JSON bytes — protobuf
        # requests/accepts take the worker path (http.py _wants_proto)
        if "protobuf" in headers.get("content-type", "") or (
            "protobuf" in headers.get("accept", "")
        ):
            return None
        t0 = time.perf_counter()
        try:
            pql = body.decode()
        except UnicodeDecodeError:
            return None
        entry = cache.lookup_pql(self.api, index, pql, shards)
        if entry is None:
            return None
        close = "close" in headers.get("connection", "").lower()
        head = (
            "HTTP/1.1 200 OK\r\n"
            f"Server: pilosa-tpu/{__version__}\r\n"
            "Content-Type: application/json\r\n"
            f"Content-Length: {entry.nbytes}\r\n"
            + ("Connection: close\r\n" if close else "")
            + "\r\n"
        ).encode()
        writer.write(head + entry.body)
        try:
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            return False
        elapsed = time.perf_counter() - t0
        self.stats.count("queries_served", tags={"path": "cache"})
        self._settle_cached(index, pql, shards, elapsed, entry.nbytes,
                            arrival)
        return not close

    def _settle_cached(self, index: str, pql: str,
                       shards: list[int] | None, elapsed: float,
                       nbytes: int, arrival: float | None) -> None:
        """Observability settle for a loop-served hit: the workload
        plane and flight recorder must see cached serves too, or the
        measured hit rate and the heavy-hitter ranks would go dark for
        exactly the hottest traffic.  Spill is skipped (file I/O has no
        place on the loop thread); the in-memory capture ring still
        records."""
        wl = getattr(self, "workload", None)
        fp = None
        if wl is not None and wl.enabled:
            fp, call_type = wl.fingerprint(index, pql, shards)
            wl.record(
                index, pql, fp, call_type, elapsed, 200, nbytes,
                route="cache", stamp=self.api.mutation_stamp(index),
                arrival=arrival, shards=shards, spill=False,
            )
            wl.record_cache_hit(fp)
        rec = getattr(self, "flightrec", None)
        if rec is not None and rec.enabled:
            call_type = pql.split("(", 1)[0].strip() or "?"

            def entry() -> dict:
                out = {
                    "index": index,
                    "query": pql[:500],
                    "node": self.node_id,
                    "resultCache": {"outcome": "hit"},
                }
                if fp is not None:
                    out["fingerprint"] = fp
                return out

            rec.settle(call_type, elapsed, entry)

    async def _admit_and_dispatch(self, writer, cls: str,
                                  raw: bytes, deadline,
                                  arrival: float | None = None) -> bool:
        """Admission control + worker hand-off.  Returns False when the
        connection must close."""
        adm = self._admission[cls]
        if (
            cls == _CLASS_WRITE
            and self.compaction_max_debt > 0
            and self.compaction_debt is not None
            and self.compaction_debt() > self.compaction_max_debt
        ):
            self._reject("compaction_debt")
            # the write path is ahead of compaction capacity: shed the
            # write at the door (429, keep-alive intact — the body was
            # fully consumed) instead of letting ops logs and crash-
            # replay time grow without bound (docs/durability.md)
            await self._write_simple(
                writer, 429,
                "compaction debt exceeds compaction-max-debt; retry",
                retry_after="1", close=False,
            )
            return True
        if adm.depth > 0 and adm.waiting >= adm.depth:
            self._reject("queue_full")
            # bounded queues are the backpressure contract: shed load
            # HERE with a Retry-After hint instead of queueing into
            # deadline exhaustion (docs/serving.md); keep-alive survives
            # — the body was fully consumed, framing is intact
            await self._write_simple(
                writer, 429,
                f"admission queue full for {cls} requests; retry",
                retry_after="1", close=False,
            )
            return True
        self.stats.observe(
            "admission_queue_depth", float(adm.waiting), tags={"class": cls}
        )
        adm.waiting += 1
        t0 = time.monotonic()
        try:
            await adm.sem.acquire()
        finally:
            adm.waiting -= 1
        wait_s = time.monotonic() - t0
        self.stats.timing(
            "admission_wait_seconds", wait_s, tags={"class": cls},
        )
        adm.in_flight += 1
        try:
            if deadline is not None and deadline.expired():
                # the labeled 504 (docs/fault-tolerance.md): the budget
                # died in the admission queue — never execute
                self._reject("deadline")
                await self._write_simple(
                    writer, 504,
                    f"query deadline exceeded ({deadline.budget_s * 1e3:.0f}ms "
                    "budget exhausted in admission queue)",
                    close=False,
                )
                return True
            loop = asyncio.get_running_loop()
            # the worker may ship bytes straight to the socket ONLY when
            # nothing is queued in the transport: drain() waits for the
            # high-water mark, not empty, so a slow-reading client can
            # leave a prior response's tail buffered — a direct send then
            # would interleave behind-the-transport bytes on the wire.
            # Checked here (loop thread) and monotone: the loop never
            # writes during BUSY, so an empty buffer stays empty.
            direct_ok = (
                self.ssl_context is None
                and writer.transport.get_write_buffer_size() == 0
            )
            payload, close = await loop.run_in_executor(
                self._pool, self._run_request, raw, writer, deadline,
                direct_ok, wait_s, arrival,
            )
        finally:
            adm.in_flight -= 1
            adm.sem.release()
        if payload:
            # remainder the worker's direct send couldn't ship (full
            # socket buffer, or the TLS path): the transport owns the
            # backpressure from here
            writer.write(payload)
            await writer.drain()
        return not close

    def _run_request(self, raw: bytes, writer, deadline,
                     direct_ok: bool = False,
                     admission_wait: float | None = None,
                     arrival: float | None = None) -> tuple[bytes, bool]:
        """Worker-thread half: run the buffered request through the
        route table; returns (unsent response bytes, close_connection).

        Plaintext responses are shipped straight from the worker with a
        single non-blocking send: the client's reply must not wait on
        an event-loop wakeup (~0.5ms of cross-thread signaling on a
        busy host) — the loop's own resume overlaps the client's next
        request instead.  Safe because exactly one writer touches a
        connection while a request is dispatched (the loop never writes
        during BUSY, the sweeper skips BUSY), and ``direct_ok`` is set
        only when the loop saw the transport buffer EMPTY at dispatch —
        a slow-reading client with a prior response's tail still queued
        gets its reply through the transport, in order.  Whatever the
        socket buffer cannot take — and the whole payload on TLS
        connections, where the transport owns the record layer —
        returns to the loop."""
        peer = writer.get_extra_info("peername") or ("", 0)
        try:
            h = _BufferedHandler(self, raw, peer, deadline, admission_wait,
                                 arrival)
            out = h.wfile.getvalue()
            close = h.close_connection
            if not out:
                out, close = (
                    self._plain_error(500, "handler produced no response"),
                    True,
                )
        except Exception as e:  # pilosa: allow(broad-except) — last-resort
            # mapping: Handler._guarded catches handler errors, so only
            # parser/shim bugs land here; they must cost one 500, not a
            # silently dropped connection
            self.log(f"buffered handler error: {e!r}")
            out, close = self._plain_error(500, f"internal: {e!r}"), True
        if direct_ok:
            sock = writer.get_extra_info("socket")
            if sock is not None:
                try:
                    sent = os.write(sock.fileno(), out)
                    out = out[sent:]
                except (BlockingIOError, InterruptedError):
                    pass  # kernel buffer full: the loop ships the rest
                except (OSError, ValueError):
                    return b"", True  # client went away; loop closes
        return out, close

    # ------------------------------------------------------------ responses
    @staticmethod
    def _plain_error(code: int, message: str) -> bytes:
        import json as _json

        body = _json.dumps({"error": message}).encode()
        head = (
            f"HTTP/1.1 {code} {_REASONS.get(code, 'Error')}\r\n"
            f"Server: pilosa-tpu/{__version__}\r\n"
            "Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode()
        return head + body

    async def _write_simple(self, writer, code: int, message: str,
                            retry_after: str | None = None,
                            close: bool = False) -> None:
        import json as _json

        body = _json.dumps({"error": message}).encode()
        lines = [
            f"HTTP/1.1 {code} {_REASONS.get(code, 'Error')}",
            f"Server: pilosa-tpu/{__version__}",
            "Content-Type: application/json",
            f"Content-Length: {len(body)}",
        ]
        if retry_after is not None:
            lines.append(f"Retry-After: {retry_after}")
        if close:
            lines.append("Connection: close")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
        try:
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass


_REASONS = {
    400: "Bad Request",
    408: "Request Timeout",
    414: "URI Too Long",
    429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error",
    501: "Not Implemented",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}


class _Admission:
    """One admission class: a concurrency semaphore (slots = worker
    threads reserved for the class) plus a bounded wait queue counted by
    ``waiting``.  All state is touched only from the event loop, so no
    lock is needed."""

    __slots__ = ("sem", "limit", "depth", "waiting", "in_flight")

    def __init__(self, limit: int, depth: int):
        self.sem = asyncio.Semaphore(limit)
        self.limit = limit
        self.depth = depth
        self.waiting = 0
        self.in_flight = 0
