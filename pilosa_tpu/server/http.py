"""HTTP transport: the reference's route surface over the API façade.

Reference: http/handler.go (gorilla/mux routes). JSON is the primary wire
format with ``application/x-protobuf`` content negotiation on the query
and import routes (reference parity; see encoding/); routes and payload
field names match the reference so existing clients port over:

    POST   /index/{index}/query?shards=0,2
    POST   /index/{index}                    DELETE /index/{index}
    GET    /index/{index}
    POST   /index/{index}/field/{field}      DELETE /index/{index}/field/{field}
    POST   /index/{index}/field/{field}/import
    POST   /index/{index}/field/{field}/import-value
    POST   /index/{index}/field/{field}/import-roaring/{shard}
    GET    /schema        POST /schema
    GET    /status  /info  /version  /metrics  /debug/vars  /debug/traces
    GET    /export?index=i&field=f
    GET    /index/{index}/field/{field}/fragment/data?shard=N[&format=pilosa|official]
    GET    /internal/fragment/nodes?index=i&shard=3
    POST   /internal/translate/keys     (JSON or protobuf TranslateKeysRequest)
    (further /internal/* data-plane routes live in the cluster layer)
"""

from __future__ import annotations

import contextlib
import json
import re
import threading
import time
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from pilosa_tpu import __version__, encoding
from pilosa_tpu.executor import ExecutionError
from pilosa_tpu.parallel import resilience
from pilosa_tpu.parallel.resilience import DeadlineExceededError
from pilosa_tpu.parallel.topology import ShardUnavailableError
from pilosa_tpu.server.api import RequestTooLargeError
from pilosa_tpu.pql import PQLError
from pilosa_tpu.utils import GLOBAL_TRACER, StatsClient
from pilosa_tpu.utils import tracing

_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("POST", re.compile(r"^/index/([^/]+)/query$"), "query"),
    ("POST", re.compile(r"^/index/([^/]+)/field/([^/]+)/import$"), "import_bits"),
    ("POST", re.compile(r"^/index/([^/]+)/field/([^/]+)/import-value$"), "import_values"),
    (
        "POST",
        re.compile(r"^/index/([^/]+)/field/([^/]+)/import-roaring/(\d+)$"),
        "import_roaring",
    ),
    ("POST", re.compile(r"^/index/([^/]+)/field/([^/]+)$"), "create_field"),
    ("DELETE", re.compile(r"^/index/([^/]+)/field/([^/]+)$"), "delete_field"),
    ("POST", re.compile(r"^/index/([^/]+)$"), "create_index"),
    ("DELETE", re.compile(r"^/index/([^/]+)$"), "delete_index"),
    ("GET", re.compile(r"^/index/([^/]+)$"), "get_index"),
    ("GET", re.compile(r"^/$"), "console"),
    ("GET", re.compile(r"^/schema$"), "get_schema"),
    ("POST", re.compile(r"^/schema$"), "post_schema"),
    ("GET", re.compile(r"^/status$"), "status"),
    ("GET", re.compile(r"^/info$"), "info"),
    ("GET", re.compile(r"^/version$"), "version"),
    ("GET", re.compile(r"^/metrics$"), "metrics"),
    ("GET", re.compile(r"^/debug/?$"), "debug_index"),
    ("GET", re.compile(r"^/debug/vars$"), "debug_vars"),
    ("GET", re.compile(r"^/debug/profile$"), "debug_profile"),
    ("GET", re.compile(r"^/debug/saturation$"), "debug_saturation"),
    ("GET", re.compile(r"^/debug/processes$"), "debug_processes"),
    ("GET", re.compile(r"^/debug/cluster$"), "debug_cluster"),
    ("GET", re.compile(r"^/debug/resources$"), "debug_resources"),
    ("GET", re.compile(r"^/debug/traces$"), "debug_traces"),
    ("GET", re.compile(r"^/debug/flightrec$"), "debug_flightrec"),
    ("GET", re.compile(r"^/debug/workload$"), "debug_workload"),
    ("GET", re.compile(r"^/debug/slo$"), "debug_slo"),
    ("GET", re.compile(r"^/debug/sanitize$"), "debug_sanitize"),
    ("GET", re.compile(r"^/debug/faults$"), "debug_faults"),
    ("POST", re.compile(r"^/debug/faults$"), "debug_faults_set"),
    ("DELETE", re.compile(r"^/debug/faults$"), "debug_faults_clear"),
    ("GET", re.compile(r"^/debug/pprof/profile$"), "pprof_profile"),
    ("GET", re.compile(r"^/debug/pprof/goroutine$"), "pprof_goroutine"),
    ("GET", re.compile(r"^/debug/pprof/heap$"), "pprof_heap"),
    ("GET", re.compile(r"^/export$"), "export"),
    (
        "GET",
        re.compile(r"^/index/([^/]+)/field/([^/]+)/fragment/data$"),
        "fragment_export",
    ),
    ("GET", re.compile(r"^/internal/fragment/nodes$"), "fragment_nodes"),
    ("POST", re.compile(r"^/internal/translate/keys$"), "translate_keys"),
]


# the debug-surface directory served by GET /debug/ — (path, one-line
# description, serves-JSON, doctor query string or None to skip in the
# `pilosa_tpu doctor` bundle).  Keep in lockstep with _ROUTES: a debug
# route absent here is invisible to operators and to doctor.
_DEBUG_ENDPOINTS: list[tuple[str, str, bool, str | None]] = [
    ("/debug/", "this directory: every debug endpoint, one line each", True, None),
    ("/debug/vars", "counters/gauges/histograms plus per-subsystem state snapshots", True, ""),
    ("/debug/profile", "continuous profiler: folded flame-graph stacks (?seconds=N, ?segment=, ?format=speedscope|segments)", False, "?format=speedscope"),
    ("/debug/saturation", "USE verdict: event-loop lag, worker utilization, GIL estimate, lock contention (?window=S)", True, ""),
    ("/debug/processes", "multi-process fleet view: supervisor state + per-process saturation verdicts stitched over localhost (?window=S)", True, ""),
    ("/debug/cluster", "cluster movement view: state, rebalance thread, per-transfer progress, throttle + throughput meter", True, ""),
    ("/debug/resources", "unified per-subsystem used/limit/pressure resource ledger", True, ""),
    ("/debug/flightrec", "retained slow/errored query evidence (?trace_id=, &format=perfetto)", True, ""),
    ("/debug/workload", "heavy-hitter fingerprints + cachability estimate (?top=, ?format=capture)", True, ""),
    ("/debug/slo", "per-call-type SLO burn rates and budget remaining", True, ""),
    ("/debug/sanitize", "concurrency sanitizer: observed lock graph, cycles, loop-thread findings (PILOSA_TPU_SANITIZE=1)", True, ""),
    ("/debug/faults", "armed fault-injection rules, RPC + filesystem (POST/DELETE to arm/clear)", True, ""),
    ("/debug/traces", "recent tracing spans (?trace_id=, ?format=chrome)", True, ""),
    ("/debug/pprof/profile", "BLOCKING on-demand sampling profile (?seconds=, default 5)", False, "?seconds=1"),
    ("/debug/pprof/goroutine", "current stack of every live thread", False, ""),
    ("/debug/pprof/heap", "top allocation sites via tracemalloc (?top=)", True, ""),
]


def snapshot_envelope(section: dict) -> dict:
    """Uniform freshness envelope for every ``/debug/vars`` section:
    ``snapshotMonotonicS`` (this process's monotonic clock — diff two
    scrapes to age a snapshot without NTP hazards) and ``generatedAt``
    (ISO-8601 UTC wall time, for correlating with external logs; never
    used in arithmetic).  Sections used to carry inconsistent timestamp
    fields — some wall-clock, most absent — so "how stale is this
    snapshot" had no uniform answer."""
    out = dict(section)
    out["snapshotMonotonicS"] = time.monotonic()
    out["generatedAt"] = datetime.now(timezone.utc).isoformat()
    return out


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "pilosa-tpu/" + __version__

    # quiet default request logging; stats cover it
    def handle_one_request(self):
        try:
            super().handle_one_request()
        except (ConnectionResetError, BrokenPipeError, TimeoutError):
            # client tore the connection down mid-request — close quietly
            # instead of spraying a per-disconnect traceback from the
            # handler thread (VERDICT r3 weak #7)
            self.close_connection = True

    def log_message(self, fmt, *args):
        pass

    @property
    def api(self):
        return self.server.api

    @property
    def stats(self) -> StatsClient:
        return self.server.stats

    def _dispatch(self, method: str) -> None:
        parsed = urlparse(self.path)
        self.query_params = parse_qs(parsed.query)
        self.route_name = ""
        # per-request response/attribution state mined by the JSON
        # access log (docs/workload.md): send_response/send_header
        # overrides fill status + bytes, h_query fills the fingerprint
        self._resp_status = 0
        self._resp_bytes = 0
        self._trace_id = None
        self._workload_fp = None
        t0 = time.perf_counter()
        # propagated trace context (coordinator → data plane): a remote
        # node's spans join the coordinator's trace and parent onto its
        # fan-out span instead of starting a disconnected trace
        trace_id = self.headers.get(tracing.TRACE_HEADER)
        parent_span = self.headers.get(tracing.PARENT_HEADER)
        with GLOBAL_TRACER.activate(trace_id, parent_span):
            for m, pattern, name in _ROUTES:
                if m != method:
                    continue
                match = pattern.match(parsed.path)
                if match:
                    self.route_name = name
                    self.stats.count("http_requests", tags={"route": name})
                    # every route pays the same span + per-route latency
                    # histogram here — handlers cannot opt out of either
                    # (the observability analyzer rule pins this down)
                    with self.stats.timer(
                        "http_request_seconds", tags={"route": name}
                    ):
                        with GLOBAL_TRACER.span(f"http.{name}") as sp:
                            self._trace_id = sp.trace_id
                            self._guarded(
                                getattr(self, "h_" + name), *match.groups()
                            )
                    self._access_log(
                        method, parsed.path, time.perf_counter() - t0
                    )
                    return
            # extra (/internal/*) routes get the same error mapping, a
            # span so remote data-plane work appears in the stitched
            # trace, and the same per-route histogram (route=internal)
            with self.stats.timer(
                "http_request_seconds", tags={"route": "internal"}
            ):
                with GLOBAL_TRACER.span("http.internal", path=parsed.path) as sp:
                    self._trace_id = sp.trace_id
                    handled = self._guarded(
                        self.server.handle_extra, self, method, parsed.path
                    )
        if handled is False:
            self._json({"error": "not found"}, code=404)
        self._access_log(method, parsed.path, time.perf_counter() - t0)

    def _access_log(self, method: str, path: str, seconds: float) -> None:
        """Structured JSON access log (config access-log-format=json,
        docs/workload.md): one line per request — method, route,
        status, latency, response bytes, trace id, and (query routes)
        the workload fingerprint — so log pipelines index requests
        without regexes.  Off by default; the status/bytes fields are
        captured by the send_response/send_header overrides below, so
        enabling it costs one json.dumps per request and nothing when
        disabled."""
        if not getattr(self.server, "access_log_json", False):
            return
        entry = {
            "event": "access",
            "method": method,
            "path": path,
            "route": self.route_name or "internal",
            "status": self._resp_status,
            "latencyMs": round(seconds * 1e3, 3),
            "bytes": self._resp_bytes,
            "traceId": self._trace_id,
        }
        if self._workload_fp is not None:
            entry["fingerprint"] = self._workload_fp
        self.server.log("access " + json.dumps(entry))

    def send_response(self, code, message=None):
        # the access log's status attribution: every response path
        # (handlers, _error, send_error) funnels through here
        self._resp_status = code
        super().send_response(code, message)

    def send_header(self, keyword, value):
        if keyword.lower() == "content-length":
            try:
                self._resp_bytes = int(value)
            except (TypeError, ValueError):
                pass
        super().send_header(keyword, value)

    def _guarded(self, fn, *args):
        """Run a route handler with the error→status mapping applied.
        The mapping itself lives in ``_error_status`` — the ONE table,
        shared with the workload capture so the recorded status can
        never drift from the status the client received."""
        try:
            return fn(*args)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response
        except Exception as e:  # pilosa: allow(broad-except) — the
            # route error chokepoint: anything a handler leaks maps to
            # a status via _error_status instead of killing the
            # connection thread
            code = self._error_status(e)
            if encoding.AVAILABLE and isinstance(e, encoding.DecodeError):
                self._error(f"bad protobuf body: {e}", code=code)
            elif code == 500:
                self._error(f"internal: {e!r}", code=code)
            else:
                self._error(str(e), code=code)
        return None

    @staticmethod
    def _error_status(e: BaseException) -> int:
        """The HTTP status a handler error maps to — the single source
        for ``_guarded`` (the response) and the workload capture (the
        recorded status).  Ordering matters only for subclass pairs:
        RequestTooLargeError subclasses ExecutionError, so 413 checks
        first; Deadline/ShardUnavailable/DecodeError are disjoint from
        the 400 group (RuntimeError / protobuf Error bases)."""
        if isinstance(e, RequestTooLargeError):
            return 413
        if isinstance(e, (ExecutionError, PQLError, ValueError, KeyError)):
            return 400
        if isinstance(e, DeadlineExceededError):
            # the labeled per-query timeout (docs/fault-tolerance.md):
            # 504, never a generic 500/503 — a budget cut is the
            # client's contract working, not a server fault
            return 504
        if isinstance(e, ShardUnavailableError):
            return 503
        if encoding.AVAILABLE and isinstance(e, encoding.DecodeError):
            return 400
        return 500

    def _error(self, msg: str, code: int) -> None:
        """Error response in the negotiated wire format (reference:
        handler errors land in QueryResponse.err / ImportResponse.err for
        protobuf clients, plain JSON otherwise). Only the query and
        import routes carry an err field in their protobuf responses;
        every other route's errors are JSON regardless of negotiation
        (e.g. translate_keys — TranslateKeysResponse has no err field)."""
        if self._wants_proto() and self.route_name.startswith("import"):
            self._proto(encoding.protoser.import_response_to_bytes(msg), code=code)
        elif self._wants_proto() and self.route_name == "query":
            self._proto(
                encoding.protoser.response_to_bytes({"results": [], "error": msg}),
                code=code,
            )
        else:
            self._json({"error": msg}, code=code)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")

    # ------------------------------------------------------------- helpers
    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _json_body(self) -> dict:
        body = self._body()
        if not body:
            return {}
        try:
            return json.loads(body)
        except json.JSONDecodeError as e:
            raise ValueError(f"bad JSON body: {e}") from e

    def _json(self, obj, code: int = 200, extra_headers: dict | None = None) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _text(self, text: str, content_type: str = "text/plain", code: int = 200) -> None:
        self._bytes(text.encode(), content_type=content_type, code=code)

    def _bytes(
        self, data: bytes, content_type: str = "application/octet-stream", code: int = 200
    ) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _shards_param(self) -> list[int] | None:
        raw = self.query_params.get("shards")
        if not raw:
            return None
        return [int(s) for s in raw[0].split(",") if s != ""]

    def _proto_body(self) -> bool:
        """True when the request body is protobuf-encoded."""
        return encoding.AVAILABLE and encoding.CONTENT_TYPE in self.headers.get(
            "Content-Type", ""
        )

    def _wants_proto(self) -> bool:
        """Content negotiation (reference: http/handler.go checks
        Content-Type/Accept for application/x-protobuf). An explicit
        ``Accept: application/json`` wins even for protobuf request
        bodies (proto-in/JSON-out)."""
        accept = self.headers.get("Accept", "")
        if "application/json" in accept:
            return False
        return self._proto_body() or (
            encoding.AVAILABLE and encoding.CONTENT_TYPE in accept
        )

    def _proto(self, data: bytes, code: int = 200, extra_headers: dict | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", encoding.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    # -------------------------------------------------------------- routes
    def _gate(self) -> bool:
        """Device-probe gate for routes whose work reaches JAX: during
        the probe window a query must not initialize the (possibly
        wedged) accelerator backend in-process — that hang is
        uninterruptible and holds JAX's process-global init lock, so the
        post-probe CPU pin could never recover (ADVICE r5 medium). The
        server-side gate waits a bounded slice for the verdict; if it is
        still pending, serve 503 + Retry-After instead of dispatching."""
        if self.server.gate():
            return True
        self._body()  # drain: an unread body would corrupt keep-alive framing
        # same wire-format negotiation as _error(), plus Retry-After — a
        # protobuf client must get a decodable QueryResponse/ImportResponse
        # error envelope, not a JSON body it can't parse
        msg = "device probe in progress; retry"
        headers = {"Retry-After": "2"}
        if self._wants_proto() and self.route_name.startswith("import"):
            self._proto(
                encoding.protoser.import_response_to_bytes(msg),
                code=503,
                extra_headers=headers,
            )
        elif self._wants_proto() and self.route_name == "query":
            self._proto(
                encoding.protoser.response_to_bytes({"results": [], "error": msg}),
                code=503,
                extra_headers=headers,
            )
        else:
            self._json({"error": msg}, code=503, extra_headers=headers)
        return False

    def _query_context(self) -> "resilience.QueryContext":
        """Per-query resilience context (docs/fault-tolerance.md): the
        deadline budget — an explicit ``X-Pilosa-Deadline-Ms`` header
        (the remaining budget of an upstream hop, or a client opting
        into a tighter bound) wins over the server's configured
        ``query-timeout-ms`` default — plus the ``?allow-partial=true``
        opt-in for labeled partial results under replica loss.

        On the event-driven front end the deadline starts ticking at
        ADMISSION, not here: the accept loop installs the Deadline it
        created when the request head arrived (docs/serving.md), so time
        spent queued behind other work counts against the budget — a
        query must never get a fresh clock just because it waited."""
        deadline = getattr(self, "admission_deadline", None)
        if deadline is None:
            deadline = resilience.deadline_from_header(
                self.headers.get(resilience.DEADLINE_HEADER)
            )
        if deadline is None and self.server.query_timeout_ms > 0:
            deadline = resilience.Deadline(self.server.query_timeout_ms / 1e3)
        allow_partial = self.query_params.get("allow-partial", [""])[
            0
        ].lower() in ("true", "1")
        return resilience.QueryContext(
            deadline=deadline, allow_partial=allow_partial
        )

    def h_query(self, index: str) -> None:
        if not self._gate():
            return
        body = self._body()
        proto = self._wants_proto()
        shards = self._shards_param()
        if self._proto_body():
            pql, req_shards = encoding.protoser.query_request_from_bytes(body)
            shards = shards or req_shards
        else:
            pql = body.decode()
        want_profile = self.query_params.get("profile", [""])[0].lower() in (
            "true",
            "1",
        )
        explain = self.query_params.get("explain", [""])[0].lower()
        if explain in ("true", "1", "plan"):
            # EXPLAIN (docs/observability.md): the plan alone — router
            # cost table per candidate path, residency classification,
            # mesh verdict, wave batchability — NOTHING executes
            plan = self.api.explain(index, pql, shards)
            self._enrich_cache_candidacy(plan, index, pql, shards)
            self._json({"explain": plan})
            return
        # EXPLAIN ANALYZE is JSON-only, like ?profile=true — a protobuf
        # QueryResponse has no explain slot, so don't pay the plan walk
        # for a payload that could never be delivered
        analyze = explain == "analyze" and not proto
        # EXPLAIN ANALYZE snapshots the plan BEFORE execution so the
        # estimates it shows are the ones this very run decided with
        # (execution feeds the calibration EWMAs, moving them)
        plan = self.api.explain(index, pql, shards) if analyze else None
        if plan is not None:
            self._enrich_cache_candidacy(plan, index, pql, shards)
        qctx = self._query_context()
        # ?profile and EXPLAIN ANALYZE must measure a REAL execution —
        # a cached serve has no per-call actuals; lookups are bypassed
        # (fills still happen: a profiled run settles a valid result)
        cache = getattr(self.api, "result_cache", None)
        bypass = (
            cache.bypass()
            if cache is not None and (want_profile or analyze)
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        err: BaseException | None = None
        resp = None
        # the profile collector is always installed (a handful of dict
        # appends per query) so the long-query log can name the slow
        # shard group even when the client didn't ask for a profile —
        # and so the flight recorder has full evidence at settle time
        # for a query nobody marked in advance
        with resilience.use_query_context(qctx):
            with tracing.profile_query() as prof:
                with self.stats.timer("query_seconds", tags={"index": index}):
                    with GLOBAL_TRACER.span("pql.query", index=index) as sp:
                        prof.trace_id = sp.trace_id
                        try:
                            with bypass:
                                resp = self.server.query_router(
                                    index, pql, shards
                                )
                        except Exception as e:  # noqa: BLE001 — held for
                            # the flight recorder's settle decision
                            # (errored queries retain), re-raised below
                            # into _guarded's canonical status mapping
                            err = e
        elapsed = time.perf_counter() - t0
        cache_out = (
            cache.consume_outcome() if cache is not None else None
        )
        prof.total_seconds = elapsed
        wait = getattr(self, "admission_wait_s", None)
        if wait is not None:
            # the event front end's admission-lane wait for THIS request
            # (docs/serving.md): the queue-or-query attribution
            prof.admission_wait = wait
        if qctx.deadline is not None:
            prof.deadline = {
                "budgetS": qctx.deadline.budget_s,
                "remainingS": qctx.deadline.remaining(),
            }
        # workload fingerprint (docs/workload.md): the query's identity
        # in the heavy-hitter sketch — computed once here (a cached
        # dict hit on repeated traffic) and shared by the flight
        # recorder entry, the slow-query log line, the access log, and
        # the capture record below
        wl = getattr(self.server, "workload", None)
        fp = wl_call = None
        if wl is not None and wl.enabled:
            fp, wl_call = wl.fingerprint(index, pql, shards)
            self._workload_fp = fp
            if (
                fp is not None
                and cache_out is not None
                and cache_out.get("outcome") == "hit"
            ):
                # measured hit next to the cachability estimate
                # (/debug/workload servableFraction vs actualHitFraction)
                wl.record_cache_hit(fp)
        self._flightrec_settle(
            index, pql, prof, elapsed, err, fp=fp, wl=wl,
            cache_out=cache_out,
        )
        if err is not None:
            self._workload_record(
                wl, fp, wl_call, index, pql, prof, elapsed,
                self._error_status(err), 0, shards=shards,
            )
            raise err
        slow = self.server.long_query_time
        if slow > 0 and elapsed >= slow:
            worst = prof.slowest()
            where = ""
            if worst is not None:
                shard_list = worst.get("shards")
                where = (
                    f" slowest={worst['call']}"
                    + (f" node={worst['node']}" if "node" in worst else "")
                    + (f" shards={shard_list}" if shard_list else "")
                    + f" ({worst['seconds']:.3f}s)"
                )
            rank = wl.rank(fp) if wl is not None and fp is not None else None
            cache_tag = (
                f" cache={cache_out['outcome']}"
                if cache_out is not None and "outcome" in cache_out
                else ""
            )
            self.server.log(
                f"long query ({elapsed:.3f}s) index={index}"
                f" trace={prof.trace_id} fp={fp} rank={rank}{cache_tag}"
                f"{where}: {pql[:200]}"
            )
        if proto:
            self._proto(encoding.protoser.response_to_bytes(resp))
        else:
            if want_profile:
                resp = dict(resp)
                resp["profile"] = prof.to_json()
            if analyze:
                resp = dict(resp)
                resp["explain"] = self._merge_explain_actuals(plan, prof)
            self._json(resp)
        # recorded AFTER the response ships so the capture carries the
        # real result size (send_header stashed Content-Length)
        self._workload_record(
            wl, fp, wl_call, index, pql, prof, elapsed, 200,
            getattr(self, "_resp_bytes", 0), shards=shards,
        )

    def _workload_record(
        self, wl, fp: str | None, call_type: str | None, index: str,
        pql: str, prof, elapsed: float, status: int, nbytes: int,
        shards: list[int] | None = None,
    ) -> None:
        """Feed the settled query to the workload plane: fingerprint →
        sketch + per-fingerprint stats + SLO windows + (sampled) the
        capture ring.  ``call_type`` comes from the fingerprinter's
        parse (never ``_readback``, which can lead prof.calls under
        wave concurrency).  The mutation stamp recorded alongside is
        the cachability signal (docs/workload.md)."""
        if wl is None or not wl.enabled or fp is None:
            return
        route = next(
            (c.get("route") for c in prof.calls if c.get("route")), None
        )
        wl.record(
            index,
            pql,
            fp,
            call_type or "?",
            elapsed,
            status,
            nbytes,
            route=route,
            trace_id=prof.trace_id,
            stamp=self.api.mutation_stamp(index),
            arrival=getattr(self, "arrival_monotonic", None),
            shards=shards,
        )

    def _flightrec_settle(
        self, index: str, pql: str, prof, elapsed: float,
        err: BaseException | None, fp: str | None = None, wl=None,
        cache_out: dict | None = None,
    ) -> None:
        """Hand the settled query to the flight recorder — the evidence
        thunk (full profile + the trace's buffered spans) is only paid
        when the recorder decides to retain.  The entry carries the
        query's workload fingerprint and its CURRENT heavy-hitter rank
        (docs/workload.md), so a retained slow query links straight to
        "how often does this exact query run" in /debug/workload."""
        rec = getattr(self.server, "flightrec", None)
        if rec is None or not rec.enabled:
            return
        if prof.calls:
            call_type = prof.calls[0]["call"]
        else:
            call_type = pql.split("(", 1)[0].strip() or "?"

        def entry() -> dict:
            out = {
                "traceId": prof.trace_id,
                "index": index,
                "query": pql[:500],
                "node": self.server.node_id,
                "profile": prof.to_json(),
                "spans": (
                    GLOBAL_TRACER.spans_for_trace(prof.trace_id)
                    if prof.trace_id
                    else []
                ),
            }
            if cache_out is not None:
                # result-cache verdict for this serve (hit/miss/skip +
                # fill outcome) — a retained slow query answers "why
                # wasn't this a cache hit" directly
                out["resultCache"] = cache_out
            if fp is not None:
                out["fingerprint"] = fp
                if wl is not None:
                    # rank is resolved lazily HERE — only retained
                    # queries pay the O(k) sketch walk
                    out["workloadRank"] = wl.rank(fp)
            sampler = getattr(self.server, "profiler", None)
            if sampler is not None and sampler.enabled:
                # continuous-profiler linkage (docs/profiling.md): the
                # segment ids overlapping this query's wall-clock window
                # — the retained slow query links straight to the flame
                # graph that contains it (/debug/profile?segment=ID)
                now = time.monotonic()
                out["profilerSegments"] = sampler.segments_overlapping(
                    now - elapsed, now
                )
            return out

        rec.settle(call_type, elapsed, entry, error=err)

    def _enrich_cache_candidacy(
        self, plan: dict, index: str, pql: str,
        shards: list[int] | None,
    ) -> None:
        """Add the MEASURED half of the EXPLAIN cache verdict: the
        structural candidacy (api.explain) knows the thresholds, the
        workload plane knows this fingerprint's measured cost and
        result size — an admitted-in-principle query whose measured
        mean cost sits below result-cache-min-cost-ms (or whose results
        exceed the per-entry byte cap) reports skipped, with why."""
        verdict = plan.get("resultCache")
        cache = getattr(self.api, "result_cache", None)
        wl = getattr(self.server, "workload", None)
        if (
            verdict is None
            or cache is None
            or wl is None
            or not wl.enabled
            or not verdict.get("admitted")
        ):
            return
        fp, _ = wl.fingerprint(index, pql, shards)
        with wl._lock:
            st = wl._fp_stats.get(fp)
            measured = st.to_json() if st is not None else None
        if measured is None:
            return
        verdict["fingerprint"] = fp
        verdict["measuredMeanMs"] = measured["meanMs"]
        mean_bytes = measured["resultBytesTotal"] / max(
            1, measured["observed"]
        )
        verdict["measuredMeanBytes"] = round(mean_bytes, 1)
        if measured["meanMs"] < cache.min_cost_ms:
            verdict["admitted"] = False
            verdict["reason"] = (
                f"measured mean cost {measured['meanMs']}ms is below "
                f"result-cache-min-cost-ms ({cache.min_cost_ms}ms) — "
                "not worth a ledger slot"
            )
        elif 0 < cache.entry_byte_cap < mean_bytes:
            verdict["admitted"] = False
            verdict["reason"] = (
                f"measured mean result size {round(mean_bytes)} bytes "
                f"exceeds the per-entry byte cap "
                f"({cache.entry_byte_cap} bytes)"
            )

    @staticmethod
    def _merge_explain_actuals(plan: dict, prof) -> dict:
        """EXPLAIN ANALYZE: attach each call's measured actuals next to
        the estimates the plan carries, plus the per-path error ratio
        for the route that actually ran."""
        actuals = [e for e in prof.calls if e["call"] != "_readback"]
        readback = sum(
            e["seconds"] for e in prof.calls if e["call"] == "_readback"
        )
        dev_calls = sum(
            1 for e in actuals if e.get("route") in ("device", "mesh")
        )
        for p, actual in zip(plan.get("calls", []), actuals):
            p["actualSeconds"] = actual["seconds"]
            actual_route = actual.get("route") or p.get("route")
            p["actualRoute"] = actual_route
            measured = actual["seconds"]
            if actual_route in ("device", "mesh") and readback:
                # the shared readback wave's cost, split across the
                # device-routed calls that rode it — same attribution
                # the router audit uses
                measured += readback / max(1, dev_calls)
            chosen = p.get("candidates", {}).get(actual_route)
            if chosen and chosen.get("estimatedSeconds"):
                chosen["measuredSeconds"] = measured
                chosen["errorRatio"] = (
                    measured / chosen["estimatedSeconds"]
                )
        plan["actualTotalSeconds"] = prof.total_seconds
        if readback:
            plan["actualReadbackSeconds"] = readback
        if prof.wave is not None:
            plan["wave"] = prof.wave
        if prof.admission_wait is not None:
            plan["admissionWaitSeconds"] = prof.admission_wait
        return plan

    def h_create_index(self, index: str) -> None:
        body = self._json_body()
        self.api.create_index(index, body.get("options", {}))
        self.server.broadcast_schema()
        self._json({"success": True})

    def h_delete_index(self, index: str) -> None:
        self.api.delete_index(index)
        self.server.broadcast_deletion(index)
        self._json({"success": True})

    def h_get_index(self, index: str) -> None:
        for idx in self.api.schema()["indexes"]:
            if idx["name"] == index:
                self._json(idx)
                return
        self._json({"error": f"index {index!r} not found"}, code=404)

    def h_create_field(self, index: str, field: str) -> None:
        body = self._json_body()
        self.api.create_field(index, field, body.get("options", {}))
        self.server.broadcast_schema()
        self._json({"success": True})

    def h_delete_field(self, index: str, field: str) -> None:
        self.api.delete_field(index, field)
        self.server.broadcast_deletion(index, field)
        self._json({"success": True})

    def _import_payload(self, values: bool) -> dict:
        if self._proto_body():
            body = self._body()
            if values:
                return encoding.protoser.import_value_request_from_bytes(body)
            return encoding.protoser.import_request_from_bytes(body)
        return self._json_body()

    def _import_ok(self) -> None:
        if self._wants_proto():
            self._proto(encoding.protoser.import_response_to_bytes())
        else:
            self._json({"success": True})

    def _record_ingest(
        self, route: str, nbytes: int, bits: int = 0, started: float | None = None
    ) -> None:
        """Ingest observability (docs/ingest.md): per-route byte/bit
        counters + the batch-latency histogram, and the rolling meter
        the /debug/resources "ingest" row reads."""
        meter = getattr(self.server, "ingest_meter", None)
        if meter is not None:
            meter.record(nbytes, bits)
        if self.stats is not None:
            self.stats.count("import_bytes_total", nbytes, tags={"route": route})
            if bits:
                self.stats.count("import_bits_total", bits)
            if started is not None:
                self.stats.timing(
                    "import_batch_seconds", time.perf_counter() - started
                )

    def h_import_bits(self, index: str, field: str) -> None:
        if not self._gate():
            return
        t0 = time.perf_counter()
        body_len = int(self.headers.get("Content-Length") or 0)
        payload = self._import_payload(values=False)
        self.server.import_router(index, field, payload, values=False)
        cols = payload.get("columnIDs")
        self._record_ingest(
            "import", body_len, len(cols) if cols is not None else 0, t0
        )
        self._import_ok()

    def h_import_values(self, index: str, field: str) -> None:
        if not self._gate():
            return
        t0 = time.perf_counter()
        body_len = int(self.headers.get("Content-Length") or 0)
        payload = self._import_payload(values=True)
        self.server.import_router(index, field, payload, values=True)
        cols = payload.get("columnIDs")
        self._record_ingest(
            "import-value", body_len, len(cols) if cols is not None else 0, t0
        )
        self._import_ok()

    def h_import_roaring(self, index: str, field: str, shard: str) -> None:
        if not self._gate():
            return
        param_view = self.query_params.get("view", [""])[0]
        if self._proto_body():
            data, view = encoding.protoser.import_roaring_request_from_bytes(
                self._body()
            )
            # envelope view wins; fall back to ?view= then "standard"
            view = view or param_view or "standard"
        else:
            data = self._body()
            view = param_view or "standard"
        t0 = time.perf_counter()
        # clustered nodes swap this router for the replica fan-out that
        # streams the SAME frame bytes to every shard owner
        bits = self.server.roaring_router(index, field, int(shard), data, view)
        self._record_ingest("import-roaring", len(data), int(bits or 0), t0)
        self._import_ok()

    def h_console(self) -> None:
        """Embedded query console (reference parity: the v0.x WebUI,
        embedded via statik; here one self-contained HTML file)."""
        import importlib.resources

        html = (
            importlib.resources.files("pilosa_tpu.server")
            .joinpath("console.html")
            .read_text(encoding="utf-8")
        )
        self._text(html, content_type="text/html; charset=utf-8")

    def h_get_schema(self) -> None:
        self._json(self.api.schema())

    def h_post_schema(self) -> None:
        self.api.apply_schema(self._json_body())
        self._json({"success": True})

    def h_status(self) -> None:
        self._json(
            {
                "state": self.api.state(),
                "nodes": self.api.hosts(),
                "localID": self.server.node_id,
                "topologyEpoch": self.api.topology_epoch(),
                # True while this node's translate stores are awaiting a
                # full reconcile (boot / post-demotion): a fencing
                # promoter pulls such unverified chains FIRST so verified
                # peers' entries win any conflict
                "translatePending": self.api.translate_pending(),
                # full per-index shard inventory piggybacks on the
                # heartbeat (reference: availableShards travels in
                # gossip ClusterStatus) — peers route reads from this
                # cache instead of polling node_shards per read
                "shards": self.api.node_inventories(),
            }
        )

    def h_info(self) -> None:
        self._json(self.api.info())

    def h_version(self) -> None:
        self._json({"version": __version__})

    def h_metrics(self) -> None:
        self._text(self.stats.prometheus(), content_type="text/plain; version=0.0.4")

    def h_debug_vars(self) -> None:
        out = self.stats.expvar()
        # every section below carries the uniform snapshotMonotonicS +
        # generatedAt envelope (snapshot_envelope): sections used to mix
        # wall-clock timestamps with none at all, so snapshot staleness
        # had no consistent answer
        # device-cache effectiveness counters (tests assert the write
        # path stays incremental; operators read them here)
        out["stackCache"] = snapshot_envelope(
            self.api.executor.compiler.stacks.stats_snapshot()
        )
        # tiered compressed residency: container tiers, hot/cold row
        # promotion + demotion, per-container resident bytes
        # (docs/device-residency.md)
        out["deviceResidency"] = snapshot_envelope(
            self.api.executor.compiler.stacks.residency_snapshot()
        )
        # live cost-router calibration: mode, crossover, and the EWMAs
        # behind every host/device decision (docs/query-routing.md)
        out["queryRouting"] = snapshot_envelope(
            self.api.executor.router.snapshot()
        )
        # settle-time router-decision audit: per-path estimate-error
        # drift and the misroute matrix (docs/query-routing.md)
        out["routerAudit"] = snapshot_envelope(
            self.api.executor.router.audit.snapshot()
        )
        # cross-query wave coalescing: waves, occupancy, dedup hits
        # (docs/query-batching.md)
        out["queryBatching"] = snapshot_envelope(self.api.scheduler.snapshot())
        # explicit-SPMD mesh execution: device count, mesh geometry,
        # per-program-family call counts, fallbacks (docs/spmd.md)
        out["meshExecution"] = snapshot_envelope(
            self.api.executor.compiler.mesh_snapshot()
        )
        # serving front end: connection counts, admission queue state,
        # per-class concurrency limits (docs/serving.md)
        out["serving"] = snapshot_envelope(self.server.serving_snapshot())
        # durable write protocol: WAL fsync mode + dirty-file count, and
        # the background compactor's queue/debt state (docs/durability.md)
        from pilosa_tpu.utils import durable

        out["durability"] = snapshot_envelope(
            {
                "wal": durable.wal_snapshot(),
                "compaction": self.api.holder.compactor.snapshot(),
            }
        )
        # workload-intelligence plane health: capture ring depth,
        # sampled/dropped counts, sketch size, spill segments — the
        # analysis itself serves at /debug/workload (docs/workload.md)
        out["workload"] = snapshot_envelope(
            self.server.workload.vars_snapshot()
        )
        # mutation-stamped result cache: ledger, hit/miss/eviction/
        # invalidation counters, admission skips (docs/result-cache.md)
        cache = getattr(self.api, "result_cache", None)
        if cache is not None:
            out["resultCache"] = snapshot_envelope(cache.snapshot())
        self._json(out)

    def h_debug_index(self) -> None:
        """``GET /debug/``: the debug-surface directory — every debug
        endpoint with a one-line description (there are a dozen now and
        nothing listed them).  ``pilosa_tpu doctor`` walks this list to
        snapshot the whole surface into one offline bundle, so a new
        debug route added HERE is automatically collected.  The
        ``doctor`` field reflects LIVE state: a healthy node with the
        profiler configured off must not make doctor exit non-zero
        over the 404 that endpoint correctly serves."""
        prof = getattr(self.server, "profiler", None)
        out = []
        for p, d, j, q in _DEBUG_ENDPOINTS:
            if p == "/debug/profile" and (prof is None or not prof.enabled):
                q = None
            out.append(
                {"path": p, "description": d, "json": j, "doctor": q}
            )
        self._json({"endpoints": out})

    def h_debug_profile(self) -> None:
        """The continuous profiler's surface (docs/profiling.md): a
        flame graph of the recent past, served instantly from the
        segment ring — nothing to arm in advance.  ``?seconds=N`` merges
        the segments covering the last N seconds, ``?segment=ID`` one
        retained historical segment (the id a flight-recorder entry
        carries), ``?format=speedscope`` speedscope.app JSON instead of
        folded text, ``?format=segments`` the ring index."""
        prof = getattr(self.server, "profiler", None)
        if prof is None:
            self._json({"error": "profiler not wired"}, code=404)
            return
        fmt = self.query_params.get("format", ["folded"])[0]
        if fmt == "segments":
            self._json(snapshot_envelope(prof.snapshot()))
            return
        if not prof.enabled:
            self._json(
                {"error": "profiler disabled (config profiler-enabled)"},
                code=404,
            )
            return
        seconds_raw = self.query_params.get("seconds", [""])[0]
        segment_raw = self.query_params.get("segment", [""])[0]
        seconds = float(seconds_raw) if seconds_raw else None
        segment = int(segment_raw) if segment_raw else None
        try:
            if fmt in ("speedscope", "json"):
                self._json(prof.speedscope(seconds=seconds, segment=segment))
            else:
                self._text(prof.folded(seconds=seconds, segment=segment))
        except KeyError as e:
            self._json({"error": str(e)}, code=404)

    def h_debug_saturation(self) -> None:
        """The USE-style saturation verdict (docs/profiling.md): event-
        loop lag, worker-pool utilization, the GIL-wait estimate, and
        hot-lock contention, each normalized to a [0,1] pressure, with
        the binding resource named for the window (``?window=S``,
        default 60)."""
        mon = getattr(self.server, "saturation", None)
        if mon is None:
            self._json({"error": "saturation monitor not wired"}, code=404)
            return
        window = float(self.query_params.get("window", ["60"])[0])
        self._json(
            snapshot_envelope(
                mon.report(
                    window_s=window, serving=self.server.serving_snapshot()
                )
            )
        )

    def h_debug_processes(self) -> None:
        """The multi-process fleet view (docs/multiprocess.md): the
        supervisor's state file (sharing mode, child pids, restart
        counts) stitched with every co-resident process's LIVE
        ``/debug/saturation`` verdict fetched over localhost.  Served
        by every child, so a client hitting the shared public port gets
        the whole fleet no matter which process the kernel picked; on
        an unsupervised node the view degrades to per-cluster-node
        verdicts (same stitch, no parent metadata).  ``?window=S``
        forwards to each saturation report (default 60)."""
        window = self.query_params.get("window", ["60"])[0]
        float(window)  # validate before forwarding into the fleet
        out: dict = {"supervised": False, "processes": []}
        state = None
        state_path = getattr(self.server, "supervisor_state_path", None)
        if state_path:
            try:
                with open(state_path) as f:
                    state = json.load(f)
            except (OSError, ValueError) as e:
                out["stateError"] = repr(e)
        if state:
            out["supervised"] = True
            for key in ("mode", "publicBind", "publicUri", "parentPid"):
                if key in state:
                    out[key] = state[key]
            members = state.get("processes", [])
        else:
            members = [
                {"uri": n.get("uri"), "id": n.get("id")}
                for n in self.api.hosts()
            ]
        for m in members:
            row = {
                k: m[k]
                for k in (
                    "index", "id", "uri", "bind", "pid", "ready",
                    "restarts", "lastExitCode",
                )
                if k in m
            }
            uri = m.get("uri") or ""
            if not uri:
                # solo node with no cluster: report the local verdict
                mon = getattr(self.server, "saturation", None)
                if mon is not None:
                    rep = mon.report(
                        window_s=float(window),
                        serving=self.server.serving_snapshot(),
                    )
                    row.update(self._saturation_digest(rep))
                out["processes"].append(row)
                continue
            try:
                rep = self._fetch_fleet_json(
                    f"{uri}/debug/saturation?window={window}"
                )
                row.update(self._saturation_digest(rep))
            except Exception as e:  # pilosa: allow(broad-except) — the
                # fleet view's JOB includes naming which process could
                # not answer (a crashed child mid-restart is the
                # interesting row, not a reason to 500 the whole view)
                row["error"] = repr(e)
            out["processes"].append(row)
        self._json(snapshot_envelope(out))

    @staticmethod
    def _saturation_digest(rep: dict) -> dict:
        """The per-process slice of a /debug/saturation report the
        fleet view stitches: verdict + pressures + sharing mode, not
        the full probe histograms (doctor bundles those per node)."""
        digest = {
            "binding": rep.get("binding"),
            "verdict": rep.get("verdict"),
            "pressures": rep.get("pressures"),
            "sharedListener": (rep.get("serving") or {}).get(
                "sharedListener"
            ),
            "connectionsOpen": (rep.get("serving") or {}).get(
                "connectionsOpen"
            ),
        }
        if "recommendation" in rep:
            digest["recommendation"] = rep["recommendation"]
        return digest

    def _fetch_fleet_json(self, url: str, timeout: float = 5.0) -> dict:
        import ssl
        import urllib.request

        ctx = None
        if url.startswith("https://"):
            # co-resident children share the node's own (often self-
            # signed) certificate — verification adds nothing on
            # localhost and would break the default TLS recipe
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        req = urllib.request.Request(url)
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as r:
            return json.loads(r.read() or b"{}")

    def h_debug_cluster(self) -> None:
        """The cluster movement view (docs/resize.md): cluster state +
        topology epoch, whether a rebalance pull is in flight, every
        IN-FLIGHT transfer's progress row (direction, fragment, peer,
        bytes, age), recent completions, and the movement meter
        (window Mbit/s, throttle waits) — the surface an operator
        watches while adding or draining a node."""
        cluster = getattr(self.api, "cluster", None)
        if cluster is None:
            # solo fallback, the /debug/processes precedent: the surface
            # stays probeable (doctor bundles every /debug/ endpoint) and
            # says there is no movement plane rather than erroring
            self._json(snapshot_envelope({"clustered": False}))
            return
        t = cluster._rebalance_thread
        self._json(
            snapshot_envelope({
                "clustered": True,
                "state": cluster.state,
                "localID": cluster.me.id,
                "topologyEpoch": cluster.topology.epoch,
                "rebalance": {
                    "inFlight": bool(t is not None and t.is_alive()),
                    "thread": t.name if t is not None else None,
                },
                "movement": cluster.movement.snapshot(),
            })
        )

    def h_debug_resources(self) -> None:
        """The unified resource ledger (docs/profiling.md): the byte
        accounting scattered across the codebase — device residency
        ledger, WAL/ops-log debt, compaction debt, the capture/tracer/
        flight-recorder rings, connections, workers, process RSS —
        consolidated into one per-subsystem used/limit/pressure view,
        sorted so the fullest subsystem reads first."""
        from pilosa_tpu.utils import durable, saturation
        from pilosa_tpu.utils.tracing import MAX_SPANS

        subs: dict[str, dict] = {}

        def row(name: str, used, limit, unit: str, **extra) -> None:
            pressure = (
                round(used / limit, 4) if limit else None
            )
            subs[name] = {
                "used": used,
                "limit": limit or None,
                "unit": unit,
                "pressure": pressure,
                **extra,
            }
            if self.stats is not None and pressure is not None:
                self.stats.gauge(
                    "resource_pressure", pressure, tags={"subsystem": name}
                )
            if unit == "bytes" and self.stats is not None:
                self.stats.gauge(
                    "resource_bytes", float(used), tags={"subsystem": name}
                )

        # device residency: the stack cache's aggregate byte ledger.
        # The budget is read WITHOUT forcing resolution — the HBM query
        # initializes the JAX backend, and this control-plane route does
        # not pass the device-probe gate (limit reads None until a
        # query resolved it)
        from pilosa_tpu.executor import compile as query_compile

        stacks = self.api.executor.compiler.stacks
        row(
            "deviceResidency",
            stacks.resident_bytes,
            query_compile.stack_budget_if_resolved(),
            "bytes",
        )
        # WAL / ops-log debt (crash-replay bytes) + compaction queue
        wal = self.api.holder.wal_ledger()
        row(
            "walOpsLog",
            wal["opsLogBytes"],
            None,
            "bytes",
            pendingOps=wal["pendingOps"],
            fragments=wal["fragments"],
            maxOpLogFill=wal["maxOpLogFill"],
            fsync=durable.wal_snapshot(),
        )
        comp = self.api.holder.compactor
        debt = comp.debt()
        max_debt = getattr(self.server, "compaction_max_debt", 0) or 0
        row("compaction", debt, max_debt, "compactions",
            workers=comp.workers)
        # bulk-ingest lane (docs/ingest.md): rolling window throughput +
        # lifetime totals from the import routes' meter
        meter = getattr(self.server, "ingest_meter", None)
        if meter is not None:
            ing = meter.snapshot()
            row(
                "ingest",
                ing["bytesTotal"],
                None,
                "bytes",
                bitsTotal=ing["bitsTotal"],
                postsTotal=ing["postsTotal"],
                windowSeconds=ing["windowSeconds"],
                recentBytesPerS=ing["recentBytesPerS"],
                recentMbitSetPerS=ing["recentMbitSetPerS"],
            )
        # movement lane (docs/resize.md): bulk data movement byte totals
        # + window rate, with slot occupancy as the pressure fraction
        cluster = getattr(self.api, "cluster", None)
        if cluster is not None:
            mv = cluster.movement.snapshot()
            row(
                "movement",
                len(mv["active"]),
                mv["maxConcurrent"],
                "transfers",
                bytesTotal=mv["meter"]["bytesTotal"],
                fragmentsTotal=mv["meter"]["fragmentsTotal"],
                throttleWaits=mv["meter"]["throttleWaits"],
                recentMbitPerS=mv["meter"]["recentMbitPerS"],
                maxMbit=mv["maxMbit"],
            )
        # evidence rings
        rec = getattr(self.server, "flightrec", None)
        if rec is not None:
            row("flightrecRing", len(rec.entries()), rec.capacity, "entries",
                enabled=rec.enabled)
        wl = getattr(self.server, "workload", None)
        if wl is not None:
            ws = wl.vars_snapshot()
            row("workloadCaptureRing", ws["captureRingDepth"],
                ws["captureRingCapacity"], "entries", enabled=ws["enabled"])
            row("workloadSpill", ws["spillSegments"], None, "segments",
                pendingRecords=ws["spillPendingRecords"])
        # result-cache byte ledger (docs/result-cache.md): used vs the
        # result-cache-bytes budget; the row() helper publishes the
        # resource_bytes{subsystem="result-cache"} gauge alongside
        cache = getattr(self.api, "result_cache", None)
        if cache is not None:
            cs = cache.snapshot()
            row(
                "result-cache",
                cs["usedBytes"],
                cs["maxBytes"] or None,
                "bytes",
                entries=cs["entries"],
                hits=cs["hits"],
                misses=cs["misses"],
                evictions=cs["evictions"],
                invalidations=cs["invalidations"],
                mode=cs["mode"],
            )
        row("tracerRing", GLOBAL_TRACER.depth(), MAX_SPANS, "spans")
        # serving front end: connections + per-class worker occupancy
        serving = self.server.serving_snapshot()
        row(
            "connections",
            serving.get("connectionsOpen", 0),
            serving.get("maxConnections", 0) or None,
            "connections",
            mode=serving.get("mode"),
        )
        for cls, adm in (serving.get("admission") or {}).items():
            row(
                f"workers.{cls}",
                adm["inFlight"],
                adm["limit"],
                "threads",
                queueDepth=adm["queueDepth"],
                queueCap=adm["queueCap"],
            )
        # process memory against the cgroup ceiling (if any)
        rss = saturation.rss_bytes()
        if rss is not None:
            row("processRss", rss, saturation.memory_limit_bytes(), "bytes",
                threads=threading.active_count())
        ranked = sorted(
            subs,
            key=lambda k: -(subs[k]["pressure"] or 0.0),
        )
        self._json(
            snapshot_envelope(
                {
                    "subsystems": {k: subs[k] for k in ranked},
                    "fullest": (
                        ranked[0]
                        if ranked and subs[ranked[0]]["pressure"]
                        else None
                    ),
                }
            )
        )

    def h_debug_flightrec(self) -> None:
        """The flight recorder's surface (docs/observability.md):
        retained slow/errored query evidence.  ``?trace_id=`` returns
        one entry with the full profile and spans;
        ``?trace_id=&format=perfetto`` (or ``chrome``) exports the
        retained spans as Chrome trace-event JSON — loadable in
        Perfetto even after the live tracer ring rotated them out."""
        rec = getattr(self.server, "flightrec", None)
        if rec is None:
            self._json({"error": "flight recorder not wired"}, code=404)
            return
        trace_id = self.query_params.get("trace_id", [""])[0]
        fmt = self.query_params.get("format", [""])[0]
        if trace_id:
            if fmt in ("perfetto", "chrome"):
                out = rec.perfetto(trace_id, node_id=self.server.node_id)
                if out is None:
                    self._json(
                        {"error": f"trace {trace_id!r} not retained"}, code=404
                    )
                    return
                self._json(out)
                return
            e = rec.entry(trace_id)
            if e is None:
                self._json(
                    {"error": f"trace {trace_id!r} not retained"}, code=404
                )
                return
            self._json(e)
            return
        self._json(rec.snapshot())

    def h_debug_workload(self) -> None:
        """The workload-intelligence report (docs/workload.md): top-K
        heavy-hitter fingerprints with per-fingerprint latency/churn
        stats and the cachability estimate.  ``?top=N`` bounds the
        listing; ``?format=capture`` exports the sampled capture ring
        as JSONL — directly consumable by ``pilosa_tpu replay`` (the
        zero-config capture→replay path; spill segments on disk are
        the durable alternative)."""
        wl = getattr(self.server, "workload", None)
        if wl is None:
            self._json({"error": "workload plane not wired"}, code=404)
            return
        fmt = self.query_params.get("format", [""])[0]
        if fmt == "capture":
            body = "".join(
                json.dumps(r, separators=(",", ":")) + "\n"
                for r in wl.capture_records()
            )
            self._bytes(body.encode(), content_type="application/x-ndjson")
            return
        top = int(self.query_params.get("top", ["20"])[0])
        self._json(wl.report(top=top))

    def h_debug_slo(self) -> None:
        """Per-call-type SLO state (docs/workload.md): burn rates over
        the 5m/1h windows, budget remaining, and the parsed targets.
        Gauges republish on scrape so /metrics agrees with this view."""
        wl = getattr(self.server, "workload", None)
        if wl is None:
            self._json({"error": "workload plane not wired"}, code=404)
            return
        wl.slo.publish_gauges()
        self._json(wl.slo.snapshot())

    def h_debug_sanitize(self) -> None:
        """Concurrency-sanitizer report (docs/concurrency.md): the
        observed holds-A-while-acquiring-B lock graph, per-lock hold
        times, lock-order cycles, event-loop-thread blocking acquires,
        and — when PILOSA_TPU_SANITIZE_STATIC points at the analyzer's
        --emit-lock-graph output — observed edges the static call-graph
        closure failed to predict.  Inert (enabled=false) unless the
        process started with PILOSA_TPU_SANITIZE=1."""
        from pilosa_tpu.utils import sanitize

        self._json(sanitize.report())

    def h_debug_traces(self) -> None:
        """Recent spans, or one trace by id. ``?trace_id=`` filters to a
        single trace; with ``format=chrome`` the cluster layer (when
        attached) fetches that trace's remote spans from every peer via
        GET /internal/trace and stitches one Perfetto-loadable file —
        the coordinating HTTP span with each node's spans nested inside
        on its own process track."""
        trace_id = self.query_params.get("trace_id", [""])[0]
        chrome = self.query_params.get("format", [""])[0] == "chrome"
        if chrome:
            if trace_id:
                fetch = self.server.trace_fetch
                by_node = (
                    fetch(trace_id)
                    if fetch is not None
                    else {
                        self.server.node_id: GLOBAL_TRACER.spans_for_trace(
                            trace_id
                        )
                    }
                )
                self._json(tracing.chrome_trace_stitched(by_node))
            else:
                self._json(GLOBAL_TRACER.chrome_trace())
        elif trace_id:
            self._json({"spans": GLOBAL_TRACER.spans_for_trace(trace_id)})
        else:
            self._json({"spans": GLOBAL_TRACER.recent()})

    # fault-injection debug surface (docs/fault-tolerance.md): inspect,
    # arm, and clear this node's OUTGOING data-plane fault rules at
    # runtime — chaos rehearsal on a live cluster without a restart
    def _fault_injector(self):
        inj = self.server.fault_injector
        if inj is None:
            raise ValueError(
                "fault injection is not wired on this server (runtime "
                "Server instances install an injector at open())"
            )
        return inj

    def h_debug_faults(self) -> None:
        out = self._fault_injector().snapshot()
        fs = getattr(self.server, "fs_fault_injector", None)
        if fs is not None:
            # filesystem fault layer (docs/durability.md): read-only
            # here — FS rules arm via config (fs-fault-rules), because
            # installing the process-wide hook mid-flight would race
            # in-progress write protocols
            out["fs"] = fs.snapshot()
        self._json(out)

    def h_debug_faults_set(self) -> None:
        body = self._json_body()
        rules = body.get("rules", [])
        if not isinstance(rules, list):
            raise ValueError("'rules' must be a JSON list of fault rules")
        self._fault_injector().set_rules(rules, seed=body.get("seed"))
        self._json({"success": True, "rules": len(rules)})

    def h_debug_faults_clear(self) -> None:
        self._fault_injector().clear()
        self._json({"success": True})

    # /debug/pprof analogue (reference: net/http/pprof in http/handler.go)
    def h_pprof_profile(self) -> None:
        from pilosa_tpu.utils import profiling

        seconds = float(self.query_params.get("seconds", ["5"])[0])
        self._text(profiling.sample_profile(seconds), content_type="text/plain")

    def h_pprof_goroutine(self) -> None:
        from pilosa_tpu.utils import profiling

        self._text(profiling.thread_dump(), content_type="text/plain")

    def h_pprof_heap(self) -> None:
        from pilosa_tpu.utils import profiling

        top = int(self.query_params.get("top", ["50"])[0])
        self._json(profiling.heap_profile(top))

    def h_export(self) -> None:
        index = self.query_params.get("index", [None])[0]
        field = self.query_params.get("field", [None])[0]
        if not index or not field:
            raise ValueError("export requires index= and field= params")
        shard = self.query_params.get("shard", [None])[0]
        csv = self.api.export_csv(index, field, int(shard) if shard else None)
        self._text(csv, content_type="text/csv")

    def h_fragment_export(self, index: str, field: str) -> None:
        """Serialized fragment bitmap; ?format=pilosa|official selects the
        cookie-12348 fragment layout or the stock-client 32-bit
        RoaringFormatSpec (reference analogue: RetrieveShardFromURI, made
        public so stock roaring tooling can pull fragments)."""
        shard = self.query_params.get("shard", ["0"])[0]
        view = self.query_params.get("view", ["standard"])[0]
        fmt = self.query_params.get("format", ["pilosa"])[0]
        data = self.api.fragment_data(index, field, int(shard), view, fmt)
        self._bytes(data, content_type="application/octet-stream")

    def h_translate_keys(self) -> None:
        """String keys → IDs (reference: POST /internal/translate/keys).
        Accepts a protobuf TranslateKeysRequest or JSON
        {"index", "field"?, "keys", "lookupOnly"?}; replies in kind
        (errors are always JSON — TranslateKeysResponse has no err
        field). Unknown keys on a lookup-only request come back as 0.
        Goes through the server's translate_router so the cluster layer
        can forward ID allocation to the translate primary."""
        if self._proto_body():
            req = encoding.protoser.translate_keys_request_from_bytes(self._body())
        else:
            j = self._json_body()
            req = {
                "index": j.get("index", ""),
                "field": j.get("field", ""),
                "keys": j.get("keys", []),
                "create": not j.get("lookupOnly", False),
            }
        ids = self.server.translate_router(
            req["index"], req["field"] or None, req["keys"], req["create"]
        )
        if self._wants_proto():
            self._proto(encoding.protoser.translate_keys_response_to_bytes(ids))
        else:
            self._json({"ids": [i or 0 for i in ids]})

    def h_fragment_nodes(self) -> None:
        index = self.query_params.get("index", [None])[0]
        shard = self.query_params.get("shard", ["0"])[0]
        if not index:
            raise ValueError("index= required")
        self._json(self.api.shard_nodes(index, int(shard)))


class _ServerCore:
    """Front-end-independent server state: the API binding, the router
    hooks the cluster layer swaps in, and the /internal extra-route
    table.  Shared by the event-driven listener (server/eventloop.py —
    the default) and the legacy thread-per-request listener below, so
    the cluster layer and the runtime Server wire one attribute surface
    regardless of serving mode."""

    def _init_core(self, api, stats: StatsClient | None) -> None:
        self.ssl_context = None  # set by Server.open() for TLS serving
        self.api = api
        self.stats = stats or StatsClient()
        self.node_id = "local"
        self.long_query_time = 0.0
        # per-query deadline default (config query-timeout-ms; 0 = off)
        self.query_timeout_ms = 0.0
        # the runtime Server installs its FaultInjector here so the
        # /debug/faults routes drive the same rule set the node's
        # outgoing data-plane client consults
        self.fault_injector = None
        # ... and its FSFaultInjector (docs/durability.md) so GET
        # /debug/faults reports the armed disk-fault rules too
        self.fs_fault_injector = None
        # device-probe gate: the runtime Server swaps in a hook that
        # blocks query/import dispatch (bounded) until the backend probe
        # verdict lands — True = proceed, False = serve 503 + Retry-After
        self.gate = lambda: True
        # cluster layer swaps in a cross-node trace collector:
        # trace_id -> {node_id: [span dicts]} for stitched chrome export
        self.trace_fetch = None
        # the runtime Server replaces this with its configured Logger's
        # log; the default gives standalone HTTPServers the same sink
        from pilosa_tpu.utils.log import Logger

        self.log = Logger().log
        # always-on flight recorder (docs/observability.md): tail-based
        # retention of slow/errored query evidence, served by GET
        # /debug/flightrec.  Default-constructed so embedded/standalone
        # listeners record too; Server.open replaces it with the
        # config-sized one.  The log thunk indirects through self so the
        # runtime Server's later log swap is picked up.
        from pilosa_tpu.utils.flightrec import FlightRecorder

        self.flightrec = FlightRecorder(
            stats=self.stats, log=lambda msg: self.log(msg)
        )
        # workload-intelligence plane (docs/workload.md): continuous
        # query capture + heavy-hitter sketch + SLO engine, fed by
        # h_query at every settle.  Default-constructed like the flight
        # recorder so embedded/standalone listeners measure too;
        # Server.open replaces it with the config-sized one.
        from pilosa_tpu.utils.workload import WorkloadPlane

        self.workload = WorkloadPlane(
            stats=self.stats, log=lambda msg: self.log(msg)
        )
        # mutation-stamped cross-request result cache (docs/result-
        # cache.md): default-constructed like the flight recorder so
        # embedded/standalone listeners serve repeats from settled
        # results too; Server.open replaces it with the config-sized
        # one.  Attached to the API façade — consult/fill live in
        # API.query, the cluster coordinator consults before fan-out.
        from pilosa_tpu.utils.resultcache import ResultCache

        self.result_cache = ResultCache(stats=self.stats)
        api.result_cache = self.result_cache
        self.workload.cache_byte_cap = self.result_cache.entry_byte_cap
        # continuous sampling profiler (docs/profiling.md): Server.open
        # installs a config-sized, STARTED SamplingProfiler; embedded/
        # standalone listeners leave it None (/debug/profile 404s) —
        # starting a sampler thread must be an explicit choice
        self.profiler = None
        # saturation probes (docs/profiling.md): default-constructed so
        # the event loop's lag probe and the lock families report even
        # on embedded listeners; the GIL probe thread only starts when
        # Server.open calls saturation.start()
        from pilosa_tpu.utils.saturation import SaturationMonitor

        self.saturation = SaturationMonitor(stats=self.stats)
        # structured JSON access log (config access-log-format=json);
        # off by default — the access-log emitter checks this flag
        self.access_log_json = False
        # multi-process fleet state (docs/multiprocess.md): the runtime
        # Server points this at the supervisor's state file so GET
        # /debug/processes can stitch the fleet; None = unsupervised
        self.supervisor_state_path = None
        self.extra_routes: dict = {}
        # sync queries land in the API façade, which hands them to the
        # cross-query wave scheduler (api.scheduler) instead of calling
        # the executor directly — concurrent clients share device
        # dispatch/readback waves (docs/query-batching.md)
        self.query_router = lambda index, pql, shards: api.query(index, pql, shards)
        self.import_router = self._local_import
        # bulk-lane twin of import_router: the cluster layer swaps this
        # for the replica fan-out (identical frame bytes to all owners)
        self.roaring_router = self._local_roaring
        # ingest throughput meter behind the /debug/resources "ingest"
        # row and the import_* metric family (docs/ingest.md)
        from pilosa_tpu.utils.stats import IngestMeter

        self.ingest_meter = IngestMeter()
        # cluster layer swaps this for a primary-forwarding version — ID
        # allocation on a non-primary node would fork the key space
        self.translate_router = (
            lambda index, field, keys, create: api.translate_keys(
                index, field, keys, create=create
            )
        )
        self.broadcast_schema = lambda: None
        self.broadcast_deletion = lambda index, field=None: None

    def _local_import(self, index: str, field: str, payload: dict, values: bool) -> None:
        if values:
            self.api.import_values(index, field, payload)
        else:
            self.api.import_bits(index, field, payload)

    def _local_roaring(
        self, index: str, field: str, shard: int, data: bytes, view: str
    ) -> int:
        return self.api.import_roaring(index, field, shard, data, view=view)

    def handle_extra(self, handler: Handler, method: str, path: str) -> bool:
        for (m, pattern), fn in self.extra_routes.items():
            if m == method:
                match = pattern.match(path)
                if match:
                    fn(handler, *match.groups())
                    return True
        return False

    def serving_snapshot(self) -> dict:
        """Serving-front-end state for /debug/vars (docs/serving.md);
        the event-driven listener overrides with live admission state."""
        return {"mode": "threaded"}


class ThreadedHTTPServer(_ServerCore, ThreadingHTTPServer):
    """Legacy thread-per-request front end (config serving-mode =
    "threaded"): one OS thread parks per in-flight request, so cheap
    queries regress under fan-in (BENCH_SWEEP_r06_cpu: c32 = 0.88x c1)
    and connect storms exhaust the accept backlog.  Kept as a rollback
    path and as the latency baseline the event-driven front end is
    benchmarked against (bench_all config8); it has no admission
    control — do not put it in front of high-fan-in traffic."""

    daemon_threads = True

    def handle_error(self, request, client_address):
        import sys

        exc = sys.exc_info()[1]
        if isinstance(
            exc,
            (ConnectionResetError, BrokenPipeError, TimeoutError,
             ConnectionAbortedError),
        ):
            return  # routine client teardown, not a server fault
        if self.ssl_context is not None:
            import ssl

            if isinstance(exc, ssl.SSLError):
                # failed/aborted client handshake (plaintext speaker on
                # the TLS port, cert rejected by a strict client): the
                # client's problem, logged by the client — a per-event
                # server traceback would spray the log under portscans
                return
        super().handle_error(request, client_address)

    def __init__(self, addr: tuple[str, int], api, stats: StatsClient | None = None):
        super().__init__(addr, Handler)
        self._init_core(api, stats)

    def get_request(self):
        """Accept, then wrap per-connection for TLS with the handshake
        DEFERRED (do_handshake_on_connect=False): get_request runs on the
        single accept thread, so an inline handshake would let one stalled
        client (TCP open, no ClientHello) wedge every other request; the
        deferred handshake happens on first recv in the handler's thread."""
        sock, addr = super().get_request()
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(
                sock, server_side=True, do_handshake_on_connect=False
            )
        return sock, addr

    def process_request_thread(self, request, client_address):
        # name the per-connection thread so profiler samples attribute
        # to the listener subsystem instead of "Thread-12"
        threading.current_thread().name = "http-threaded-conn"
        super().process_request_thread(request, client_address)

    def serve_background(self) -> threading.Thread:
        t = threading.Thread(
            target=self.serve_forever, daemon=True, name="http-accept"
        )
        t.start()
        return t


# the default front end: the asyncio accept/read/write loop with
# keep-alive multiplexing and bounded admission (docs/serving.md).
# Imported at the bottom so eventloop.py can subclass Handler above;
# the name HTTPServer stays here because the runtime Server, the
# cluster tests, and the package __init__ all import it from this
# module.
from pilosa_tpu.server.eventloop import EventHTTPServer as HTTPServer  # noqa: E402
