"""Programmatic API façade — the single surface the HTTP layer and tests
call.

Reference: api.go (pilosa.API: Query, CreateIndex/Field, DeleteIndex/Field,
Import, ImportValue, ImportRoaring, Schema, ApplySchema, ExportCSV,
ShardNodes, Hosts, State, Info). Serialization of results to JSON lives
here so transport layers stay thin.
"""

from __future__ import annotations

import io
import re
import time
from datetime import datetime
from typing import Any

import numpy as np

from pilosa_tpu import __version__
from pilosa_tpu.core import (
    EXISTENCE_FIELD,
    VIEW_STANDARD,
    Field,
    FieldOptions,
    Holder,
    Index,
    IndexOptions,
)
from pilosa_tpu.executor import ExecutionError, Executor, RowResult
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.utils import durable


# index/field naming rule (reference: validateName in pilosa.go — lowercase
# start, then lowercase/digit/underscore/dash, max 64 chars)
_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,63}$")


class RequestTooLargeError(ExecutionError):
    """A single request carries more writes than max_writes_per_request
    allows (reference: server/config.go max-writes-per-request). The HTTP
    layer maps this to 413."""


def validate_name(name: str, what: str = "name") -> str:
    if not _NAME_RE.fullmatch(name):
        raise ExecutionError(
            f"invalid {what} {name!r}: must match [a-z][a-z0-9_-]* "
            "and be at most 64 characters"
        )
    return name


def field_options_from_json(opts: dict, explicit_create: bool = False) -> FieldOptions:
    """Map the reference's JSON field-options wire names onto FieldOptions
    (reference: http/handler.go postFieldRequest).

    Range tracking: an explicit ``hasRange`` always wins. Without it, the
    CREATE route treats a present min/max key as a declared range (so an
    operator's explicit [0, 0] is enforced), but schema RESTORES/sync use
    the nonzero rule — pre-hasRange /schema dumps serialize min:0/max:0
    unconditionally for unbounded fields, and reading those as an
    enforced [0, 0] would brick every restored int field."""
    if "hasRange" in opts:
        has_range = bool(opts["hasRange"])
    elif explicit_create:
        has_range = "min" in opts or "max" in opts
    else:
        has_range = bool(opts.get("min", 0) or opts.get("max", 0))
    return FieldOptions(
        field_type=opts.get("type", "set"),
        cache_type=opts.get("cacheType", "ranked"),
        cache_size=opts.get("cacheSize", 50_000),
        time_quantum=opts.get("timeQuantum", ""),
        keys=opts.get("keys", False),
        min=opts.get("min", 0),
        max=opts.get("max", 0),
        has_range=has_range,
        no_standard_view=opts.get("noStandardView", False),
    )


class API:
    def __init__(
        self,
        holder: Holder,
        cluster=None,
        stats=None,
        mesh_ctx=None,
        max_writes: int = 5000,
        router=None,
        batch_mode: str | None = None,
        batch_window_us: float | None = None,
        batch_max_queries: int | None = None,
    ):
        self.holder = holder
        self.cluster = cluster  # None ⇒ single-node
        self.max_writes = max_writes
        if mesh_ctx == "auto":
            # explicit opt-in: multi-device host ⇒ serve queries as SPMD
            # programs over the device mesh (the reference's mapReduce
            # scatter-gather becomes XLA collectives; SURVEY §4.2). NOT
            # the default — MeshContext.auto() initializes the full JAX
            # backend, which must never be a construction side effect
            # (Server.open attaches the mesh after the listener binds).
            from pilosa_tpu.parallel.mesh import MeshContext

            mesh_ctx = MeshContext.auto()
        self.mesh_ctx = mesh_ctx
        self.stats = stats
        self.executor = Executor(
            holder, mesh_ctx=mesh_ctx, stats=stats, router=router
        )
        # cross-query wave scheduler (executor/scheduler.py): sync
        # queries submitted concurrently share device dispatch/readback
        # waves. Bound to a GETTER, not the executor instance, so the
        # late mesh attach (attach_mesh swaps the Executor) never
        # strands queued queries on a dead engine.
        from pilosa_tpu.executor.scheduler import WaveScheduler

        self.scheduler = WaveScheduler(
            lambda: self.executor,
            stats=stats,
            mode=batch_mode,
            window_us=batch_window_us,
            max_queries=batch_max_queries,
        )
        self.diagnostics = None  # set by Server.open
        # mutation-stamped cross-request result cache (utils/
        # resultcache.py, docs/result-cache.md).  None ⇒ uncached:
        # the serving front ends install one (_ServerCore default,
        # Server.open config-sized) — a bare API façade in tests keeps
        # its exact pre-cache semantics.
        self.result_cache = None

    def attach_mesh(self, mesh_ctx) -> None:
        """Late mesh attachment (Server.open does this after the HTTP
        listener is up so backend init never blocks the bind). The query
        router carries over: its calibration (measured dispatch/readback
        EWMAs) must survive the executor swap."""
        self.mesh_ctx = mesh_ctx
        self.executor = Executor(
            self.holder,
            mesh_ctx=mesh_ctx,
            stats=self.stats,
            router=self.executor.router,
        )

    # ------------------------------------------------------------- schema
    def create_index(self, name: str, options: dict | None = None) -> Index:
        validate_name(name, "index name")
        opts = options or {}
        idx = self.holder.create_index(
            name,
            IndexOptions(
                keys=opts.get("keys", False),
                track_existence=opts.get("trackExistence", True),
            ),
        )
        return idx

    def delete_index(self, name: str) -> None:
        self.holder.delete_index(name)
        self._invalidate_results(name)

    def create_field(self, index: str, name: str, options: dict | None = None) -> Field:
        validate_name(name, "field name")
        idx = self._index(index)
        f = idx.create_field(
            name, field_options_from_json(options or {}, explicit_create=True)
        )
        self._invalidate_results(index)
        return f

    def delete_field(self, index: str, name: str) -> None:
        self._index(index).delete_field(name)
        self._invalidate_results(index)

    def schema(self) -> dict:
        return {"indexes": self.holder.schema()}

    def apply_schema(self, schema: dict, validate: bool = True) -> None:
        """Idempotently create everything in a schema dump (reference:
        api.ApplySchema). ``validate=False`` is for cluster schema sync:
        replication must accept names that predate (or bypass) the
        create-time validation rule, or a node could fail to join against
        existing data."""
        for idx_def in schema.get("indexes", []):
            if validate:
                validate_name(idx_def["name"], "index name")
            opts = idx_def.get("options", {})
            idx = self.holder.create_index_if_not_exists(
                idx_def["name"],
                IndexOptions(
                    keys=opts.get("keys", False),
                    track_existence=opts.get("trackExistence", True),
                ),
            )
            for f_def in idx_def.get("fields", []):
                if validate:
                    validate_name(f_def["name"], "field name")
                if idx.field(f_def["name"]) is None:
                    idx.create_field(
                        f_def["name"], field_options_from_json(f_def.get("options", {}))
                    )
        for idx_def in schema.get("indexes", []):
            # schema application changes what keys/fields resolve —
            # every named index's cached results are stale generations
            self._invalidate_results(idx_def["name"])
        if self.cluster is not None:
            # a keyed store learned AFTER this node's promotion fence was
            # stamped would allocate from an empty counter (the fence
            # pulled nothing for a store it didn't know existed) — any
            # schema application invalidates the fence; re-fencing on the
            # next allocation is cheap
            with self.cluster._translate_fence_lock:
                self.cluster._translate_fence_ok = False

    # -------------------------------------------------------------- query
    def check_write_limit(self, n: int, what: str) -> None:
        if self.max_writes > 0 and n > self.max_writes:
            raise RequestTooLargeError(
                f"{what} carries {n} writes; max_writes_per_request is "
                f"{self.max_writes}"
            )

    def count_query_writes(self, calls: list) -> int:
        """Write calls in a parsed query — same classification rule the
        cluster router uses (executor.unwrap_options)."""
        from pilosa_tpu.executor.executor import WRITE_CALLS, unwrap_options

        return sum(1 for c in calls if unwrap_options(c).name in WRITE_CALLS)

    def query(
        self, index: str, pql: str, shards: list[int] | None = None
    ) -> dict:
        from pilosa_tpu.pql import parse

        calls = parse(pql) if isinstance(pql, str) else pql
        n_writes = self.count_query_writes(calls)
        self.check_write_limit(n_writes, "query")
        if self.stats is not None and self.cluster is None:
            # single-node served-query counter; clustered serving counts
            # per fan-out leg in parallel/cluster.py instead
            self.stats.count("queries_served", tags={"path": "local"})
        # read queries consult the result cache BEFORE execution: the
        # key embeds the index's current mutation stamp, so a hit is a
        # settled answer computed under this exact data generation
        # (docs/result-cache.md); key + invalidation generation are
        # snapshotted pre-execution so a result computed before a
        # concurrent write can never be stored under post-write state
        cache = self.result_cache
        key = gen = None
        if cache is not None and cache.enabled and isinstance(pql, str):
            # teach the event-loop fast path this text's identity (the
            # loop itself never parses — docs/result-cache.md)
            cache.memoize_pql(pql, None if n_writes else calls)
        if n_writes == 0 and cache is not None and cache.enabled:
            key = self._result_cache_key(index, calls, shards)
            if key is not None:
                hit = cache.get(key)
                if hit is not None:
                    return hit.resp
                gen = cache.generation(index)
        t0 = time.perf_counter()
        # sync queries go to the wave scheduler, not straight to
        # execute: concurrent device-routed requests coalesce into
        # shared dispatch/readback waves (writes and host-routed reads
        # pass through direct — see executor/scheduler.py)
        results = self.scheduler.execute(index, calls, shards=shards)
        elapsed = time.perf_counter() - t0
        if n_writes:
            # durability barrier BEFORE the acknowledgement leaves: in
            # batch WAL mode this group-fsyncs every ops log the query
            # dirtied (docs/durability.md)
            durable.ack_barrier()
            self._invalidate_results(index)
        resp = self.build_response(results)
        if key is not None:
            cache.offer(key, resp, elapsed, gen=gen)
        return resp

    def explain(self, index: str, pql: str, shards: list[int] | None = None) -> dict:
        """EXPLAIN (plan only — docs/observability.md): the decisions
        the serving path would make for this query, without executing
        it — per-call router cost tables over every candidate path,
        residency classification of touched row ranges, mesh
        supportability verdicts, and the wave scheduler's batchability
        prediction.  ``?explain=analyze`` runs the query too and the
        HTTP layer merges measured actuals next to these estimates."""
        from pilosa_tpu.executor.executor import WRITE_CALLS, unwrap_options
        from pilosa_tpu.pql import parse

        calls = parse(pql) if isinstance(pql, str) else pql
        idx = self.executor.holder.index(index)
        if idx is None:
            raise ExecutionError(f"index {index!r} not found")
        plans = [self.executor.explain_call(idx, c, shards) for c in calls]
        has_write = any(unwrap_options(c).name in WRITE_CALLS for c in calls)
        any_device = any(p.get("route") in ("device", "mesh") for p in plans)
        if self.scheduler.mode == "off":
            batchable, why = False, "batch-mode is off"
        elif has_write:
            batchable, why = False, "query contains writes (never coalesced)"
        elif not any_device:
            batchable, why = False, (
                "no device/mesh-routed call — host-routed queries bypass "
                "the wave window"
            )
        else:
            batchable, why = True, (
                "device-routed reads ride shared dispatch/readback waves"
            )
        router = self.executor.router
        return {
            "index": index,
            "query": pql if isinstance(pql, str) else repr(pql),
            "routeMode": router.mode,
            "crossoverWords": router.crossover_words(),
            "waveScheduler": {
                "mode": self.scheduler.mode,
                "batchable": batchable,
                "reason": why,
                "occupancyEwma": router.wave_occupancy.value,
            },
            "resultCache": self._explain_result_cache(
                index, calls, shards, has_write
            ),
            "calls": plans,
        }

    def _explain_result_cache(
        self, index: str, calls: list, shards, has_write: bool
    ) -> dict:
        """EXPLAIN's cache verdict (docs/result-cache.md): whether this
        exact key is cached RIGHT NOW, and the structural admission
        candidacy.  The HTTP layer enriches the verdict with the
        workload plane's measured per-fingerprint cost/bytes."""
        cache = self.result_cache
        if cache is None:
            return {"enabled": False, "reason": "no result cache wired"}
        out = {"enabled": cache.enabled, "mode": cache.mode}
        key = (
            self._result_cache_key(index, calls, shards)
            if not has_write
            else None
        )
        out["cachedNow"] = key is not None and cache.contains(key)
        out.update(cache.candidacy(index, has_write))
        return out

    def _result_cache_key(self, index: str, calls: list, shards) -> tuple | None:
        """This query's single-flight dedup identity (executor/
        scheduler.py dedup_key) — the result cache's key.  None when
        the index is gone (the caller's execution will raise the
        canonical error)."""
        idx = self.holder.index(index)
        if idx is None:
            return None
        from pilosa_tpu.executor.scheduler import dedup_key

        return dedup_key(index, calls, shards, idx)

    def _invalidate_results(self, index: str) -> None:
        """The write-path invalidation hook: EVERY API write path must
        reach this (enforced by the cacheinvariant analyzer rule).
        Correctness for stamp-blind attribute writes, byte reclamation
        for stamp-bumping ones (docs/result-cache.md)."""
        cache = self.result_cache
        if cache is not None:
            cache.invalidate(index)

    def mutation_stamp(self, index: str) -> tuple | None:
        """The index's current view-version mutation stamp — the SAME
        stack token single-flight dedup keys on (executor/scheduler.py),
        read here for the workload plane's cachability estimate
        (docs/workload.md): a repeated fingerprint whose stamp is
        unchanged between repeats is exactly a query a mutation-stamped
        result cache would have served from cache.  None when the index
        is gone (the settle races a delete).  Cost: the same
        O(fields × views) walk stack_token documents — microseconds on
        realistic schemas; if schemas grow to thousands of fields, take
        the per-index max-stamp O(1) upgrade described there and both
        callers get it."""
        idx = self.holder.index(index)
        if idx is None:
            return None
        from pilosa_tpu.executor.scheduler import stack_token

        return stack_token(idx)

    def build_response(self, results: list[Any]) -> dict:
        """Assemble the QueryResponse dict; Options(columnAttrs=true)
        results contribute response-level columnAttrs sets (reference:
        QueryResponse.ColumnAttrSets)."""
        resp: dict = {"results": [self._result_json(r) for r in results]}
        col_sets = [
            s
            for r in results
            if isinstance(r, RowResult) and r.column_attr_sets
            for s in r.column_attr_sets
        ]
        if col_sets:
            resp["columnAttrs"] = col_sets
        return resp

    def _result_json(self, r: Any) -> Any:
        if isinstance(r, RowResult):
            return r.to_json()
        if r is None:
            return None
        return r

    # ------------------------------------------------------------- import
    def import_bits(self, index: str, field: str, payload: dict) -> None:
        """Bulk bit import (reference: api.Import / ImportRequest).

        payload keys: rowIDs|rowKeys, columnIDs|columnKeys, timestamps
        (epoch seconds or ISO strings, optional), clear (optional).
        """
        idx = self._index(index)
        f = self._field(idx, field)
        # size-check the raw payload BEFORE key translation so an
        # oversized keyed import doesn't allocate new IDs first
        self.check_write_limit(self._payload_size(payload), "import")
        rows = self._resolve_rows(f, payload)
        cols = self._resolve_cols(idx, payload)
        if rows.size != cols.size:
            raise ExecutionError("rowIDs and columnIDs length mismatch")
        timestamps = None
        raw_ts = payload.get("timestamps")
        if raw_ts:
            timestamps = [self._parse_ts(t) for t in raw_ts]
        f.import_bulk(rows, cols, timestamps=timestamps, clear=payload.get("clear", False))
        idx.mark_columns_exist(cols)
        durable.ack_barrier()  # acknowledged ⇒ on disk (docs/durability.md)
        self._invalidate_results(index)

    def import_values(self, index: str, field: str, payload: dict) -> None:
        """Bulk BSI import (reference: api.ImportValue)."""
        idx = self._index(index)
        f = self._field(idx, field)
        self.check_write_limit(self._payload_size(payload), "import")
        cols = self._resolve_cols(idx, payload)
        if payload.get("clear"):
            f.clear_values(cols)
            durable.ack_barrier()
            self._invalidate_results(index)
            return
        values = np.asarray(payload.get("values", []), dtype=np.int64)
        if cols.size != values.size:
            raise ExecutionError("columnIDs and values length mismatch")
        f.import_values(cols, values)
        idx.mark_columns_exist(cols)
        durable.ack_barrier()  # acknowledged ⇒ on disk (docs/durability.md)
        self._invalidate_results(index)

    def import_roaring(self, index: str, field: str, shard: int, data: bytes, view: str = VIEW_STANDARD) -> int:
        """Direct roaring-bitmap union into a fragment (reference:
        api.ImportRoaring fast path). The wire-speed bulk lane
        (docs/ingest.md): the fragment adopts the incoming frame with
        ONE crc32-framed WAL append, and the single ``ack_barrier``
        below group-fsyncs it together with the existence-field appends
        — fsyncs amortize across concurrent importers instead of a full
        durable snapshot per post."""
        idx = self._index(index)
        if field == EXISTENCE_FIELD:
            # whole-fragment movement (rebalance pull, handoff push,
            # restore) ships the internal existence field too, and the
            # adopter may not have lazily created it yet — materialize
            # it instead of failing the transfer (docs/resize.md)
            f = idx.existence_field()
            if f is None:
                raise ExecutionError(
                    f"index {index!r} does not track existence"
                )
        else:
            f = self._field(idx, field)
        frag = f.create_view_if_not_exists(view).create_fragment_if_not_exists(shard)
        delta = frag.import_roaring(data)
        # existence marking from the DELTA (incoming positions), not the
        # merged fragment — a whole-fragment values() pass per import
        # made repeated bulk loads O(fragment) each. Folded CONTAINER-
        # wise (fold_to_columns: key arithmetic + OR chain), never a
        # value-vector sort: the per-import existence sort was the next
        # bottleneck once the adopt itself went to one WAL append.
        # Under the fragment lock: on the fresh-adopt path ``delta`` IS
        # live storage, and a concurrent writer mutating its containers
        # mid-fold would tear it.
        from pilosa_tpu.roaring.build import fold_to_columns

        with frag._lock:
            bits = delta.count()
            delta_cols = fold_to_columns(delta, SHARD_WIDTH)
        idx.mark_shard_columns(shard, delta_cols)
        # acknowledged ⇒ on disk: the barrier group-fsyncs the
        # fragment's union-frame append AND the existence-field appends
        # in one pass (docs/durability.md, docs/ingest.md)
        durable.ack_barrier()
        self._invalidate_results(index)
        # adopted bit count (the delta, deduplicated) — ingest metering
        return int(bits)

    @staticmethod
    def _payload_size(payload: dict) -> int:
        # `v is not None` (not truthiness): framed internal imports carry
        # these as ndarrays, whose truth value is ambiguous
        return max(
            (
                len(v) if (v := payload.get(k)) is not None else 0
                for k in ("rowIDs", "rowKeys", "columnIDs", "columnKeys", "values")
            ),
            default=0,
        )

    def _resolve_rows(self, f: Field, payload: dict) -> np.ndarray:
        if "rowKeys" in payload and payload["rowKeys"]:
            if not f.options.keys:
                raise ExecutionError(f"field {f.name!r} does not use string keys")
            ids = f.row_keys.translate_keys(payload["rowKeys"], create=True)
            return np.asarray(ids, dtype=np.uint64)
        return np.asarray(payload.get("rowIDs", []), dtype=np.uint64)

    def _resolve_cols(self, idx: Index, payload: dict) -> np.ndarray:
        if "columnKeys" in payload and payload["columnKeys"]:
            if not idx.options.keys:
                raise ExecutionError(f"index {idx.name!r} does not use string keys")
            ids = idx.column_keys.translate_keys(payload["columnKeys"], create=True)
            return np.asarray(ids, dtype=np.uint64)
        return np.asarray(payload.get("columnIDs", []), dtype=np.uint64)

    @staticmethod
    def _parse_ts(t: Any) -> datetime | None:
        if t in (None, 0, ""):
            return None
        if isinstance(t, (int, float)):
            return datetime.utcfromtimestamp(t)
        return datetime.fromisoformat(t)

    # -------------------------------------------------------- translation
    def _translate_store(self, index: str, field: str | None):
        """The keyed index's column store or keyed field's row store;
        validates the keys option (shared by the local path and the
        cluster's primary-forwarding router)."""
        idx = self._index(index)
        if field:
            f = self._field(idx, field)
            if not f.options.keys:
                raise ExecutionError(f"field {field!r} does not use string keys")
            return f.row_keys
        if not idx.options.keys:
            raise ExecutionError(f"index {index!r} does not use string keys")
        return idx.column_keys

    def translate_keys(
        self, index: str, field: str | None, keys: list[str], create: bool = True
    ) -> list[int | None]:
        """String keys → IDs for a keyed index (column keys) or field
        (row keys). ``create=False`` (lookup-only) leaves unknown keys as
        None — the wire layer maps them to 0, IDs start at 1. Creation is
        a write: the max_writes_per_request limit applies. Reference:
        api.TranslateKeys via POST /internal/translate/keys."""
        store = self._translate_store(index, field)
        if create:
            self.check_write_limit(len(keys), "translate")
        ids = store.translate_keys(keys, create=create)
        if create:
            # new key→id assignments are acknowledged state: a client
            # that writes bits under a returned id after a crash must
            # find the same mapping on replay
            durable.ack_barrier()
            # a fresh mapping changes what keyed queries resolve to
            # without touching any view version — stamp-blind, so the
            # explicit hook is the only correctness mechanism here
            self._invalidate_results(index)
        return ids

    # ------------------------------------------------------------- export
    def fragment_data(
        self,
        index: str,
        field: str,
        shard: int,
        view: str = VIEW_STANDARD,
        fmt: str = "pilosa",
    ) -> bytes:
        """One fragment's bitmap, serialized. ``fmt``: "pilosa" (the
        cookie-12348 fragment file layout) or "official" (32-bit
        RoaringFormatSpec — what stock CRoaring/RoaringBitmap clients
        parse; only representable when every row id < 2^32/SHARD_WIDTH,
        since the interchange format is 32-bit)."""
        from pilosa_tpu import roaring

        if fmt not in ("pilosa", "official"):
            raise ExecutionError(f"unknown roaring format {fmt!r}")
        idx = self._index(index)
        f = self._field(idx, field)
        v = f.view(view)
        frag = v.fragment(shard) if v is not None else None
        bm = frag.bitmap if frag is not None else roaring.Bitmap()
        if fmt == "official":
            return roaring.serialize_official(bm)
        return roaring.serialize(bm)

    def export_csv(self, index: str, field: str, shard: int | None = None) -> str:
        """CSV rows of (rowID/key, columnID/key) pairs (reference:
        api.ExportCSV)."""
        idx = self._index(index)
        f = self._field(idx, field)
        view = f.view(VIEW_STANDARD)
        out = io.StringIO()
        if view is None:
            return ""
        shards = sorted(view.available_shards())
        if shard is not None:
            shards = [s for s in shards if s == shard]
        for s in shards:
            frag = view.fragment(s)
            for row in frag.row_ids():
                row_repr = (
                    f.row_keys.translate_id(row) or str(row)
                    if f.options.keys
                    else str(row)
                )
                for col in frag.row_columns(row).tolist():
                    col_repr = (
                        idx.column_keys.translate_id(col) or str(col)
                        if idx.options.keys
                        else str(col)
                    )
                    out.write(f"{row_repr},{col_repr}\n")
        return out.getvalue()

    # -------------------------------------------------------------- info
    def info(self) -> dict:
        out = {
            "shardWidth": SHARD_WIDTH,
            "version": __version__,
        }
        if self.diagnostics is not None:
            out["diagnostics"] = self.diagnostics.snapshot()
        return out

    def state(self) -> str:
        return self.cluster.state if self.cluster is not None else "NORMAL"

    def hosts(self) -> list[dict]:
        if self.cluster is not None:
            return [n.to_json() for n in self.cluster.nodes]
        return [{"id": "local", "uri": "", "isCoordinator": True}]

    def topology_epoch(self) -> int:
        return self.cluster.topology.epoch if self.cluster is not None else 0

    def translate_pending(self) -> bool:
        return (
            self.cluster._translate_reconcile_pending
            if self.cluster is not None
            else False
        )

    def node_inventories(self) -> dict:
        return {
            name: sorted(idx.available_shards())
            for name, idx in self.holder.indexes.items()
        }

    def shard_nodes(self, index: str, shard: int) -> list[dict]:
        if self.cluster is not None:
            return [n.to_json() for n in self.cluster.shard_nodes(index, shard)]
        return self.hosts()

    # ------------------------------------------------------------ helpers
    def _index(self, name: str) -> Index:
        idx = self.holder.index(name)
        if idx is None:
            raise ExecutionError(f"index {name!r} not found")
        return idx

    @staticmethod
    def _field(idx: Index, name: str) -> Field:
        f = idx.field(name)
        if f is None:
            raise ExecutionError(f"field {name!r} not found")
        return f
