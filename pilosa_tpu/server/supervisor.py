"""Multi-process serving supervisor (docs/multiprocess.md).

BENCH_PROFILE_r12 measured the one-process ceiling directly: past c32
the query lane's worker-pool utilization p95 pins at 1.0 and the GIL
wait p99 reaches ~51ms — more threads cannot help, because the binding
resources are per-interpreter.  This module treats one box like a
cluster instead: ``pilosa_tpu server --processes N`` runs the parent as
a SUPERVISOR that spawns N child server processes, each a full event-
loop front end owning a disjoint shard subset through the ordinary
cluster membership (seeds over localhost, child 0 coordinator, the
configured replica-n).  Fragments are on-disk snapshots + WAL, so
ownership is purely a config statement — no storage rewrite.

Public-port sharing, two modes:

- **reuseport** — every child additionally binds the public host:port
  with ``SO_REUSEPORT`` once its cluster join completes (readiness
  gating: the kernel only balances new connections across sockets that
  exist, so a child that cannot serve its shard subset yet is simply
  not in the group).  The kernel load-balances accepts; no parent hop
  on the data path.
- **fd-pass** — where ``SO_REUSEPORT`` is missing/broken (the boot
  probe decides, loudly), the parent binds the public port, accepts,
  and ships each connected fd to a ready child over a per-child unix
  socket via ``SCM_RIGHTS``; the child adopts the fd into its event
  loop (server/eventloop.py ``add_fd_listener``).

The supervisor monitors children — restart-on-crash with capped
exponential backoff, graceful SIGTERM drain — and maintains a fleet-
state JSON (listener mode, pids, restart counts) that children read to
serve the stitched ``GET /debug/processes`` view.  The parent process
deliberately imports neither jax nor the server runtime: it is a
lifecycle manager, not a query engine.

Reference topology note: per-process shard ownership over localhost is
the same shape as per-host ownership over the DCN (arXiv 2112.09017's
multi-host recipe) — this supervisor doubles as the single-box
rehearsal of that deployment (docs/multiprocess.md §multi-host).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

from pilosa_tpu.utils import durable
from pilosa_tpu.utils.config import Config
from pilosa_tpu.utils.log import Logger

# listen backlog for the fd-pass parent's public socket — same sizing
# rationale as the event loop's (eventloop.py LISTEN_BACKLOG)
_BACKLOG = 1024
# a child alive this long resets its consecutive-crash streak: distinct
# crashes minutes apart should each pay the BASE backoff, not climb
HEALTHY_RESET_S = 30.0
# last-resort 503 the fd-pass parent answers when no child is ready
_NO_CHILD_503 = (
    b"HTTP/1.1 503 Service Unavailable\r\n"
    b"Content-Type: application/json\r\n"
    b"Retry-After: 1\r\n"
    b"Content-Length: 35\r\n"
    b"Connection: close\r\n\r\n"
    b'{"error": "no serving child ready"}'
)


def probe_so_reuseport(host: str = "127.0.0.1") -> bool:
    """Can two live sockets share one (host, port) via SO_REUSEPORT?

    Binding a second socket to the first's port is the real capability
    — the constant existing is not enough (some kernels/filesystems
    expose it and still refuse the second bind), so probe by doing."""
    if not hasattr(socket, "SO_REUSEPORT"):
        return False
    s1 = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s2 = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s1.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s1.bind((host, 0))
        port = s1.getsockname()[1]
        s2.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s2.bind((host, port))
        return True
    except OSError:
        return False
    finally:
        s1.close()
        s2.close()


def restart_backoff(consecutive: int, base_s: float, max_s: float) -> float:
    """Seconds to wait before the Nth consecutive respawn (N >= 1):
    capped exponential — base, 2·base, 4·base, ... up to max."""
    if consecutive <= 0:
        return 0.0
    return min(max_s, base_s * (2.0 ** (consecutive - 1)))


class _Child:
    """One supervised serving process: its immutable spec (index,
    internal bind, data dir, env) plus live lifecycle state."""

    def __init__(self, index: int, bind: str, data_dir: str, env: dict):
        self.index = index
        self.bind = bind  # internal 127.0.0.1:port (cluster plane)
        self.data_dir = data_dir
        self.env = env
        self.proc: subprocess.Popen | None = None
        self.ready = False
        self.restarts = 0
        self.consecutive = 0  # crash streak (reset after HEALTHY_RESET_S)
        self.spawned_at = 0.0
        self.restart_at = 0.0  # monotonic respawn-not-before
        self.last_exit: int | None = None
        self.fd_sock: socket.socket | None = None  # fd-pass control conn

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None


class Supervisor:
    """Parent of a ``serving-processes = N`` fleet: spawn, watch,
    restart, drain.  Construct with the PARENT's effective config (its
    ``bind`` is the shared public address); ``config_path`` is passed
    through to children so file-level knobs apply fleet-wide, with the
    supervisor's per-child env overrides (env beats file) layered on."""

    def __init__(self, config: Config, config_path: str | None = None,
                 argv_overrides: dict | None = None):
        if config.serving_processes < 1:
            raise ValueError("serving-processes must be >= 1")
        self.config = config
        self.config_path = config_path
        # CLI overrides that must reach children as env (CLI argv wins
        # over env in the child, so only pass-through keys belong here)
        self.argv_overrides = dict(argv_overrides or {})
        self.n = config.serving_processes
        self.logger = Logger(
            os.path.expanduser(config.log_path) if config.log_path else None
        )
        self.root = os.path.expanduser(config.data_dir)
        self.state_path = os.path.join(self.root, "supervisor.json")
        self.mode = ""  # "reuseport" | "fd-pass", decided in start()
        self.children: list[_Child] = []
        self.public_sock: socket.socket | None = None  # fd-pass only
        self._accept_thread: threading.Thread | None = None
        self._monitor_thread: threading.Thread | None = None
        self._stopping = threading.Event()
        self._rr = 0  # fd-pass round-robin cursor
        self._state_lock = threading.Lock()

    # ------------------------------------------------------------- planning
    def plan(self) -> list[_Child]:
        """Build the child specs once: stable internal ports (reused
        across restarts so peers' seed lists stay true), per-child data
        dirs under the fleet root, and the env override layer."""
        host = self.config.host
        ports = self._free_ports(host, self.n)
        binds = [f"{host}:{p}" for p in ports]
        scheme = self.config.scheme
        seeds = ",".join(f"{scheme}://{b}" for b in binds)
        children = []
        for i in range(self.n):
            env = dict(os.environ)
            env.update(
                {
                    # never recurse: a child is always a solo server
                    "PILOSA_TPU_SERVING_PROCESSES": "1",
                    # no PILOSA_TPU_NAME override: a node's id must be
                    # derived from its bind, the same way PEERS derive
                    # it from the seed list — shard ownership hashes
                    # node ids, so a vanity name here would give every
                    # member a DIFFERENT ownership map (each sees
                    # itself as "procN" but its peers as host:port)
                    "PILOSA_TPU_SEEDS": seeds,
                    "PILOSA_TPU_COORDINATOR": "1" if i == 0 else "0",
                    "PILOSA_TPU_REPLICA_N": str(self.config.replica_n),
                    "PILOSA_TPU_SUPERVISOR_STATE": self.state_path,
                }
            )
            for key, value in self.argv_overrides.items():
                env["PILOSA_TPU_" + key.upper()] = str(value)
            if self.mode == "reuseport":
                env["PILOSA_TPU_SHARED_BIND"] = self.config.bind
            else:
                env["PILOSA_TPU_FD_PASS_SOCKET"] = os.path.join(
                    self.root, f"proc{i}.sock"
                )
            children.append(
                _Child(i, binds[i], os.path.join(self.root, f"proc{i}"), env)
            )
        return children

    @staticmethod
    def _free_ports(host: str, n: int) -> list[int]:
        socks = []
        try:
            for _ in range(n):
                s = socket.socket()
                s.bind((host, 0))
                socks.append(s)
            return [s.getsockname()[1] for s in socks]
        finally:
            for s in socks:
                s.close()

    # ------------------------------------------------------------ lifecycle
    def start(self, ready_timeout_s: float = 600.0) -> None:
        """Decide the sharing mode, spawn the fleet, block until every
        child's cluster join has completed (readiness gating — the
        public port is only announced once the fleet can serve)."""
        os.makedirs(self.root, exist_ok=True)
        if probe_so_reuseport(self.config.host):
            self.mode = "reuseport"
        else:
            self.mode = "fd-pass"
            # LOUD: the operator asked for kernel-balanced sockets and
            # is getting the accept-and-pass parent instead — a real
            # throughput difference, not an implementation detail
            self.logger.log(
                "SO_REUSEPORT unavailable on this host — falling back to "
                "the accept-and-pass parent (every public connection pays "
                "one fd hand-off; docs/multiprocess.md)"
            )
        self.logger.log(
            f"supervisor: {self.n} serving processes, public port shared "
            f"via {self.mode}"
        )
        self.children = self.plan()
        if self.mode == "fd-pass":
            self.public_sock = socket.create_server(
                (self.config.host, self.config.port), backlog=_BACKLOG
            )
        self._write_state()
        for child in self.children:
            self._spawn(child)
        deadline = time.monotonic() + ready_timeout_s
        for child in self.children:
            if not self._wait_ready(child, deadline):
                raise RuntimeError(
                    f"child {child.index} ({child.bind}) not ready within "
                    f"{ready_timeout_s:.0f}s"
                )
        self._write_state()
        if self.mode == "fd-pass":
            self._accept_thread = threading.Thread(
                target=self._accept_loop, daemon=True,
                name="supervisor-accept",
            )
            self._accept_thread.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="supervisor-monitor"
        )
        self._monitor_thread.start()
        self.logger.log(
            f"supervisor: all {self.n} children ready — "
            f"{self.config.uri} announced"
        )

    def _spawn(self, child: _Child) -> None:
        argv = [
            sys.executable, "-m", "pilosa_tpu", "server",
            "--bind", child.bind,
            "--data-dir", child.data_dir,
        ]
        if self.config_path:
            argv += ["--config", self.config_path]
        child.proc = subprocess.Popen(argv, env=child.env)
        child.ready = False
        child.spawned_at = time.monotonic()
        # child.last_exit is deliberately NOT cleared: the state file's
        # lastExitCode answers "why did this child restart" long after
        # the respawn succeeded

    def _status_url(self, child: _Child) -> str:
        return f"{self.config.scheme}://{child.bind}/status"

    def _probe_ready(self, child: _Child, timeout: float = 2.0) -> bool:
        ctx = None
        if self.config.scheme == "https":
            import ssl

            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        try:
            with urllib.request.urlopen(
                self._status_url(child), timeout=timeout, context=ctx
            ) as resp:
                return json.loads(resp.read()).get("state") == "NORMAL"
        except Exception:  # pilosa: allow(broad-except) — any failure
            # (refused, reset, timeout, bad JSON) means "not ready yet"
            return False

    def _wait_ready(self, child: _Child, deadline: float) -> bool:
        while time.monotonic() < deadline and not self._stopping.is_set():
            if child.proc is not None and child.proc.poll() is not None:
                # died during boot: respawn immediately inside the
                # readiness window (a crash loop exhausts the deadline)
                child.last_exit = child.proc.returncode
                child.restarts += 1
                self.logger.log(
                    f"supervisor: child {child.index} exited "
                    f"{child.last_exit} during boot — respawning"
                )
                time.sleep(
                    restart_backoff(
                        child.restarts,
                        self.config.supervisor_restart_backoff_s,
                        self.config.supervisor_restart_backoff_max_s,
                    )
                )
                self._spawn(child)
            if self._probe_ready(child):
                child.ready = True
                child.consecutive = 0
                return True
            time.sleep(0.25)
        return child.ready

    # ------------------------------------------------------------- monitor
    def _monitor(self) -> None:
        """Watch the fleet: respawn crashed children with capped
        exponential backoff, re-confirm readiness after each respawn,
        keep the fleet-state file current."""
        while not self._stopping.is_set():
            dirty = False
            now = time.monotonic()
            for child in self.children:
                proc = child.proc
                if proc is None:
                    continue
                code = proc.poll()
                if code is not None and child.restart_at == 0.0:
                    # fresh crash: schedule the respawn
                    child.last_exit = code
                    child.ready = False
                    if child.fd_sock is not None:
                        try:
                            child.fd_sock.close()
                        except OSError:
                            pass
                        child.fd_sock = None
                    if now - child.spawned_at >= HEALTHY_RESET_S:
                        child.consecutive = 0
                    child.consecutive += 1
                    child.restarts += 1
                    delay = restart_backoff(
                        child.consecutive,
                        self.config.supervisor_restart_backoff_s,
                        self.config.supervisor_restart_backoff_max_s,
                    )
                    child.restart_at = now + delay
                    self.logger.log(
                        f"supervisor: child {child.index} "
                        f"({child.bind}) exited {code} — respawn in "
                        f"{delay:.1f}s (restart #{child.restarts})"
                    )
                    dirty = True
                elif child.restart_at and now >= child.restart_at:
                    child.restart_at = 0.0
                    self._spawn(child)
                    dirty = True
                elif (
                    not child.ready
                    and child.restart_at == 0.0
                    and code is None
                    and self._probe_ready(child, timeout=0.5)
                ):
                    # respawned child finished its rejoin: back in the
                    # fd-pass rotation / counted ready in the state file
                    child.ready = True
                    self.logger.log(
                        f"supervisor: child {child.index} rejoined "
                        "(ownership re-hydrated)"
                    )
                    dirty = True
            if dirty:
                self._write_state()
            self._stopping.wait(0.5)

    # ------------------------------------------------------- fd-pass parent
    def _accept_loop(self) -> None:
        """Accept public connections and ship each fd to a ready child
        (round-robin).  Only runs in fd-pass mode."""
        assert self.public_sock is not None
        self.public_sock.settimeout(0.5)
        while not self._stopping.is_set():
            try:
                conn, _addr = self.public_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed by stop()
            try:
                if not self._pass_fd(conn):
                    try:
                        conn.sendall(_NO_CHILD_503)
                    except OSError:
                        pass
            finally:
                # the child holds its own duplicated fd now (or the 503
                # went out); the parent's reference always closes
                conn.close()

    def _pass_fd(self, conn: socket.socket) -> bool:
        """SCM_RIGHTS hand-off to the next ready child; tries each
        child once before giving up."""
        import array

        for _ in range(len(self.children)):
            child = self.children[self._rr % len(self.children)]
            self._rr += 1
            if not child.ready:
                continue
            try:
                if child.fd_sock is None:
                    path = child.env["PILOSA_TPU_FD_PASS_SOCKET"]
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    s.connect(path)
                    child.fd_sock = s
                child.fd_sock.sendmsg(
                    [b"c"],
                    [(
                        socket.SOL_SOCKET,
                        socket.SCM_RIGHTS,
                        array.array("i", [conn.fileno()]).tobytes(),
                    )],
                )
                return True
            except OSError:
                # broken control channel: drop it, try the next child
                if child.fd_sock is not None:
                    try:
                        child.fd_sock.close()
                    except OSError:
                        pass
                    child.fd_sock = None
                continue
        return False

    # ------------------------------------------------------------ state file
    def _write_state(self) -> None:
        """Atomic fleet-state snapshot: what children serve
        /debug/processes from, and what doctor --fleet walks."""
        state = {
            "mode": self.mode,
            "publicBind": self.config.bind,
            "publicUri": self.config.uri,
            "parentPid": os.getpid(),
            "processes": [
                {
                    "index": c.index,
                    "bind": c.bind,
                    "uri": f"{self.config.scheme}://{c.bind}",
                    "dataDir": c.data_dir,
                    "pid": c.pid,
                    "ready": c.ready,
                    "restarts": c.restarts,
                    "lastExitCode": c.last_exit,
                }
                for c in self.children
            ],
        }
        tmp = self.state_path + ".tmp"
        with self._state_lock:
            with open(tmp, "w") as f:
                json.dump(state, f, indent=2)
            # best-effort observability state: atomic for readers, but a
            # crash losing the newest snapshot is fine — it is rebuilt on
            # the next monitor tick
            durable.replace_durable(tmp, self.state_path, durable=False)

    # ------------------------------------------------------------- shutdown
    def stop(self, drain_s: float = 30.0) -> None:
        """Graceful drain: stop accepting (fd-pass), SIGTERM every
        child, bounded wait, SIGKILL stragglers."""
        self._stopping.set()
        if self.public_sock is not None:
            try:
                self.public_sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=2.0)
        for child in self.children:
            if child.proc is not None and child.proc.poll() is None:
                try:
                    child.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + drain_s
        for child in self.children:
            if child.proc is None:
                continue
            try:
                child.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                self.logger.log(
                    f"supervisor: child {child.index} ignored SIGTERM for "
                    f"{drain_s:.0f}s — killing"
                )
                child.proc.kill()
                child.proc.wait(timeout=10.0)
            child.last_exit = child.proc.returncode
            child.ready = False
        self._write_state()
        self.logger.log("supervisor: fleet drained")
        self.logger.close()

    def run_forever(self) -> int:
        """CLI entry (cmd_server's --processes N path): start the
        fleet, park until SIGTERM/SIGINT, drain."""
        stop = []
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        try:
            self.start()
        except Exception:
            self.stop(drain_s=5.0)
            raise
        print(
            f"pilosa-tpu supervisor: {self.n} processes serving "
            f"{self.config.uri} ({self.mode})",
            flush=True,
        )
        try:
            while not stop:
                signal.pause()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()
        return 0
