"""L5/L6: API façade, HTTP transport, server runtime.

Reference: api.go, http/handler.go, server.go, server/ (config wiring).
"""

from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import HTTPServer
from pilosa_tpu.server.server import Server

__all__ = ["API", "HTTPServer", "Server"]
