"""Diagnostics: periodic runtime snapshots of the node.

Reference: diagnostics.go (diagnosticsCollector — hourly phone-home of
anonymized usage info). This environment has zero egress, so the
collector writes each snapshot to ``<data_dir>/diagnostics.json`` (and
keeps the latest in memory for the ``/info`` surface) instead of POSTing
it; the payload fields mirror the reference's (version, uptime, schema
shape, runtime gauges).
"""

from __future__ import annotations

import json
import os
import platform
import threading
import time


class DiagnosticsCollector:
    def __init__(self, server):
        self.server = server
        self.start_time = time.time()  # boot wall timestamp (started_at)
        # uptime measures on the monotonic clock: wall time steps under
        # NTP and a negative uptime has shipped in real diagnostics
        self._start_mono = time.monotonic()
        self._timer: threading.Timer | None = None
        self._closed = False
        self.last: dict = {}
        self._backend_cache: str | None = None

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        from pilosa_tpu import __version__

        holder = self.server.holder
        n_fields = 0
        n_fragments = 0
        field_types: dict[str, int] = {}
        # list() copies: schema writes race this timer thread
        for idx in list(holder.indexes.values()):
            for f in list(idx.fields.values()):
                n_fields += 1
                field_types[f.options.field_type] = (
                    field_types.get(f.options.field_type, 0) + 1
                )
                for view in list(f.views.values()):
                    n_fragments += len(view.fragments)
        snap = {
            "version": __version__,
            "time": time.time(),
            "uptime_seconds": round(time.monotonic() - self._start_mono, 1),
            "started_at": self.start_time,
            "node_id": self.server.config.node_id,
            "num_indexes": len(holder.indexes),
            "num_fields": n_fields,
            "num_fragments": n_fragments,
            "field_types": field_types,
            "os": platform.system(),
            "arch": platform.machine(),
            "python": platform.python_version(),
            "backend": self._backend(),
            "cluster_size": (
                len(self.server.cluster.nodes) if self.server.cluster else 1
            ),
        }
        self.last = snap
        return snap

    def _backend(self) -> str:
        # jax.devices() initializes the full backend (seconds on a TPU
        # host); compute once, off the server-startup path
        if self._backend_cache is None:
            try:
                import jax

                self._backend_cache = jax.devices()[0].platform
            except Exception:  # pilosa: allow(broad-except) — backend
                # init failures are backend-specific (RuntimeError,
                # OSError, plugin errors); diagnostics must never raise
                self._backend_cache = "unavailable"
        return self._backend_cache

    # ------------------------------------------------------------ lifecycle
    def flush(self) -> None:
        """Take a snapshot and persist it (the phone-home analogue)."""
        snap = self.snapshot()
        data_dir = os.path.expanduser(self.server.config.data_dir)
        try:
            from pilosa_tpu.utils import durable

            os.makedirs(data_dir, exist_ok=True)
            # durable=False: best-effort snapshot — atomic replace so a
            # reader never sees a torn file, no fsyncs (losing one
            # diagnostics flush to a crash costs nothing)
            durable.atomic_write_file(
                os.path.join(data_dir, "diagnostics.json"),
                json.dumps(snap, indent=1),
                tmp_suffix=".tmp",
                durable=False,
            )
        except OSError:
            pass

    def open(self) -> None:
        interval = self.server.config.diagnostics_interval
        if interval <= 0:
            return
        # first flush off the startup path — and AFTER the mesh-attach
        # verdict: _backend() initializes the JAX runtime, and doing
        # that before the server's device probe has decided the platform
        # would enter a possibly-wedged accelerator init holding jax's
        # process-global init lock, hanging every later jax call (the
        # attach thread's own CPU pin included)
        def first():
            self._gate_on_device_verdict()
            self.flush()

        self._first_flush = threading.Thread(
            target=first, daemon=True, name="diagnostics-first-flush"
        )
        self._first_flush.start()
        self._schedule(interval)

    def _gate_on_device_verdict(self) -> None:
        wait = getattr(self.server, "wait_mesh", None)
        if wait is not None:
            wait(None)

    def _schedule(self, interval: float) -> None:
        if self._closed:
            return

        def tick():
            try:
                # same gate as the first flush: a periodic flush racing
                # an undecided device probe would enter the wedged
                # backend init and hold jax's init lock before the pin
                self._gate_on_device_verdict()
                self.flush()
            finally:
                self._schedule(interval)

        self._timer = threading.Timer(interval, tick)
        self._timer.daemon = True
        self._timer.name = "diagnostics-flush"
        self._timer.start()

    def close(self) -> None:
        self._closed = True
        if self._timer is not None:
            self._timer.cancel()
