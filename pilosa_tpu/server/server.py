"""Server runtime: lifecycle wiring of holder, API, HTTP, background loops.

Reference: server.go (Server, Open, anti-entropy ticker, receiveMessage,
monitorRuntime) + server/server.go (Command wiring). Single-node by
default; passing seeds in the config attaches the cluster layer
(pilosa_tpu.parallel.cluster) which swaps in scatter-gather routers and
the /internal/* data-plane routes.
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.core import Holder
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import HTTPServer, ThreadedHTTPServer
from pilosa_tpu.utils.config import Config

# process-wide device-backend probe verdict (backends are process-global)
_DEVICE_PROBE_OK: bool | None = None
# mesh-attach failure is process-global too (same import/backend error
# for every Server); warn once, not once per server
_MESH_ATTACH_WARNED = False


class Server:
    def __init__(self, config: Config | None = None):
        self.config = config or Config()
        from pilosa_tpu.utils.stats import make_stats

        self.stats = make_stats(
            self.config.metric_service, self.config.statsd_host
        )
        from pilosa_tpu.utils.log import Logger

        self.logger = Logger(
            os.path.expanduser(self.config.log_path)
            if self.config.log_path
            else None
        )
        # WAL acknowledgement policy (docs/durability.md) is process-
        # global — set it before the holder exists so even open()-time
        # repairs write under the configured mode
        from pilosa_tpu.utils import durable

        durable.set_wal_fsync_mode(self.config.wal_fsync_mode)
        self.holder = Holder(
            os.path.expanduser(self.config.data_dir),
            compaction_workers=self.config.compaction_workers,
            load_workers=self.config.holder_load_workers,
            load_min_fragments=self.config.holder_load_min_fragments,
            stats=self.stats,
        )
        self.cluster = None
        # deterministic fault injection (docs/fault-tolerance.md):
        # always constructed — zero cost unarmed — so the /debug/faults
        # route can arm rules on a live node; the cluster's outgoing
        # client chain consults this same instance
        from pilosa_tpu.parallel.faultinject import FaultInjector, FSFaultInjector

        self.fault_injector = FaultInjector.from_config(self.config)
        # filesystem fault layer (docs/durability.md): installed process-
        # wide in open() ONLY when rules are armed — the durable write
        # protocol consults the hook at every primitive, and the chaos
        # suite needs the faults to land exactly where real disk faults
        # would. Uninstalled in close().
        self.fs_fault_injector = FSFaultInjector.from_config(self.config)
        # first-class device stack budget (docs/device-residency.md):
        # the config knob wins over the legacy PILOSA_TPU_STACK_BUDGET
        # env resolution; 0 leaves auto-resolution in place
        from pilosa_tpu.executor import compile as query_compile

        # unconditional: a 0 (auto) config must CLEAR any override a
        # previous Server in this process installed, or its budget
        # would silently leak into this one's auto-resolution
        query_compile.set_stack_budget(
            self.config.device_stack_budget_bytes or None
        )
        # per-call host/device cost router (docs/query-routing.md),
        # seeded from config; the SAME router instance survives the
        # late mesh attach so its calibration carries over
        from pilosa_tpu.executor.router import QueryRouter

        router = QueryRouter(
            mode=self.config.route_mode,
            stats=self.stats,
            dispatch_seed_s=self.config.route_dispatch_ms / 1e3,
            readback_seed_s=self.config.route_readback_ms / 1e3,
            device_wps=self.config.route_device_words_per_s,
            crossover_words=self.config.route_crossover_words,
            mesh_dispatch_seed_s=self.config.route_mesh_dispatch_ms / 1e3,
            mesh_readback_seed_s=self.config.route_mesh_readback_ms / 1e3,
            audit_enabled=self.config.router_audit_enabled,
        )
        # mesh_ctx=None here: MeshContext.auto() initializes the full JAX
        # backend (seconds, or worse on a wedged transport) — that must
        # not block Server() construction; open() attaches the mesh AFTER
        # the listener is serving (see open()'s ordering rationale)
        self.api = API(
            self.holder,
            stats=self.stats,
            mesh_ctx=None,
            max_writes=self.config.max_writes_per_request,
            router=router,
            batch_mode=self.config.batch_mode,
            batch_window_us=self.config.batch_window_us,
            batch_max_queries=self.config.batch_max_queries,
        )
        self.http: HTTPServer | None = None
        self.profiler = None
        self.diagnostics = None
        self._anti_entropy_timer: threading.Timer | None = None
        self._closed = False
        self._mesh_attach_thread: threading.Thread | None = None
        # set when the attach thread has finished (probe verdict + pin
        # decision landed). Starts UNSET so the gate holds queries from
        # the instant the listener serves — the attach thread is only
        # created later in open() (after the multihost join), and a gate
        # keyed on the thread object alone would wave traffic through
        # that window straight into an unprobed backend init.
        self._mesh_ready = threading.Event()

    def open(self) -> None:
        """holder load → HTTP up → cluster join → background loops
        (reference: Server.Open). The listener must serve BEFORE the
        cluster join: socketserver binds in the constructor, so a peer
        that probed a bound-but-not-serving node would hang in the accept
        backlog for the full client timeout instead of getting an instant
        connection-refused — concurrent cold starts then stack 30s
        timeouts on each other."""
        if (
            self.config.shared_bind or self.config.fd_pass_socket
        ) and self.config.serving_mode == "threaded":
            # refuse BEFORE any background thread starts: a misconfig
            # raising mid-open would leak profiler/saturation threads
            raise ValueError(
                "multi-process serving (shared-bind / fd-pass-socket) "
                "requires serving-mode = \"event\" — the threaded "
                "listener has no shared-listener support"
            )
        if self.fs_fault_injector.armed:
            # before holder.open(): crash-recovery rehearsals target the
            # load path (snapshot reads, torn-tail truncation) too
            from pilosa_tpu.utils import durable

            durable.install_fs_hook(self.fs_fault_injector)
        self.holder.open()
        # event-driven front end by default (docs/serving.md); the
        # legacy thread-per-request listener stays as a rollback knob
        # and as the latency baseline the bench sweep compares against
        server_cls = (
            ThreadedHTTPServer
            if self.config.serving_mode == "threaded"
            else HTTPServer
        )
        self.http = server_cls(
            (self.config.host, self.config.port), self.api, stats=self.stats
        )
        if server_cls is HTTPServer:
            # admission/backpressure knobs (docs/serving.md): these
            # replace the old fixed request_queue_size accept backlog
            self.http.max_connections = self.config.max_connections
            self.http.admission_queue_depth = self.config.admission_queue_depth
            self.http.keepalive_idle_s = self.config.keepalive_idle_s
            self.http.request_read_timeout_s = self.config.request_read_timeout_s
            self.http.worker_threads = self.config.http_worker_threads
            # write-class backpressure tied to compaction debt
            # (docs/durability.md): past the limit, imports get 429 +
            # Retry-After instead of growing ops logs without bound
            self.http.compaction_max_debt = self.config.compaction_max_debt
            self.http.compaction_debt = self.holder.compactor.debt
        if self.config.tls_certificate:
            # serve HTTPS (reference: tls.certificate/tls.key). The context
            # is handed to the listener, which wraps each accepted
            # connection with a deferred handshake — see HTTPServer.
            # get_request for why the listening socket itself must NOT be
            # wrapped (handshake would run on the accept thread).
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(
                os.path.expanduser(self.config.tls_certificate),
                os.path.expanduser(self.config.tls_key) or None,
            )
            self.http.ssl_context = ctx
        self.http.node_id = self.config.node_id
        # config-sized flight recorder (docs/observability.md) replaces
        # the listener's default one; wired to this server's logger so
        # the structured slow-query line lands in the configured sink
        from pilosa_tpu.utils.flightrec import FlightRecorder

        self.http.flightrec = FlightRecorder(
            capacity=self.config.flightrec_entries,
            min_latency_s=self.config.flightrec_min_ms / 1e3,
            stats=self.stats,
            log=self.logger.log,
            enabled=self.config.flightrec_enabled,
        )
        # config-sized workload-intelligence plane (docs/workload.md)
        # replaces the listener's default one: capture ring + durable
        # spill + heavy-hitter sketch + SLO engine. slo-targets parse
        # failures raise HERE, at boot — a typo'd objective discovered
        # when the dashboard stays empty would defeat the point.
        from pilosa_tpu.utils.workload import WorkloadPlane

        self.http.workload = WorkloadPlane(
            enabled=self.config.workload_capture_enabled,
            capacity=self.config.workload_capture_entries,
            sample_rate=self.config.workload_sample_rate,
            top_k=self.config.workload_top_k,
            capture_path=(
                os.path.expanduser(self.config.workload_capture_path)
                if self.config.workload_capture_path
                else None
            ),
            spill_max_bytes=self.config.workload_spill_max_bytes,
            spill_max_age_s=self.config.workload_spill_max_age_s,
            spill_segments=self.config.workload_spill_segments,
            slo_targets=self.config.slo_targets,
            stats=self.stats,
            log=self.logger.log,
        )
        # config-sized result cache (docs/result-cache.md) replaces the
        # listener's default one; the cache's per-entry byte cap feeds
        # the workload plane's cachability estimator so repeats of
        # never-admittable giant results stop counting as servable
        from pilosa_tpu.utils.resultcache import ResultCache

        self.http.result_cache = ResultCache(
            max_bytes=self.config.result_cache_bytes,
            min_cost_ms=self.config.result_cache_min_cost_ms,
            mode=self.config.result_cache_mode,
            stats=self.stats,
        )
        self.api.result_cache = self.http.result_cache
        self.http.workload.cache_byte_cap = (
            self.http.result_cache.entry_byte_cap
        )
        # continuous profiling + saturation plane (docs/profiling.md):
        # the config-sized sampler replaces the listener's None slot and
        # STARTS here — a flame graph of the last minute is one curl
        # away for the life of the process; the saturation monitor gets
        # the module-level metrics sink (hot locks are constructed deep
        # inside core/executor where no client is in scope) and its GIL
        # probe thread
        from pilosa_tpu.utils import saturation
        from pilosa_tpu.utils.profiler import SamplingProfiler

        saturation.set_stats(self.stats)
        self.profiler = SamplingProfiler(
            hz=self.config.profiler_hz,
            segment_s=self.config.profiler_segment_s,
            segments=self.config.profiler_segments,
            stats=self.stats,
            enabled=self.config.profiler_enabled,
        )
        self.profiler.start()
        self.http.profiler = self.profiler
        self.http.saturation = saturation.SaturationMonitor(
            stats=self.stats,
            enabled=self.config.saturation_probes_enabled,
        )
        self.http.saturation.start()
        if self.config.access_log_format not in ("", "json"):
            raise ValueError(
                "access-log-format must be \"\" or \"json\", got "
                f"{self.config.access_log_format!r}"
            )
        self.http.access_log_json = self.config.access_log_format == "json"
        self.http.long_query_time = self.config.long_query_time
        self.http.query_timeout_ms = self.config.query_timeout_ms
        self.http.fault_injector = self.fault_injector
        self.http.fs_fault_injector = self.fs_fault_injector
        self.http.log = self.logger.log
        self.http.gate = self._query_gate
        # multi-process fleet state (docs/multiprocess.md): a supervised
        # child reads the supervisor's state file to serve the stitched
        # GET /debug/processes view
        self.http.supervisor_state_path = (
            os.path.expanduser(self.config.supervisor_state)
            if self.config.supervisor_state
            else None
        )
        if self.config.seeds or self.config.coordinator:
            from pilosa_tpu.parallel.cluster import Cluster

            self.cluster = Cluster(self)
            self.api.cluster = self.cluster
            # routes/routers must be live before the first request or a
            # client could be silently served local-only (and peers 404)
            self.cluster.attach()
        self.http.serve_background()
        if self.config.coordinator_address:
            # join the static jax.distributed process group BEFORE any
            # other backend use (reference analogue: gossip join); the
            # listener is already serving so peers' health probes succeed
            # while this blocks on the coordinator barrier
            from pilosa_tpu.parallel import multihost

            multihost.init_distributed(
                self.config.coordinator_address,
                self.config.num_processes or None,
                self.config.process_id if self.config.process_id >= 0 else None,
            )
        # Device bring-up OFF-THREAD, even with the mesh disabled (the
        # probe/CPU-pin decision protects EVERY first jax use, not just
        # the mesh attach): MeshContext.auto's jax.local_devices()
        # initializes the accelerator backend, and on a tunneled device
        # a wedged transport hangs that init indefinitely (observed
        # 2026-07-31: Server.open stuck in make_c_api_client). Boot must
        # not depend on the accelerator: ingest/admin/control-plane
        # serve immediately on the host path; the mesh executor swaps in
        # when (if) the backend comes up. attach_mesh rebinds whole
        # objects, so in-flight queries see either the old or the new
        # executor.
        t = threading.Thread(
            target=self._attach_mesh_when_ready, daemon=True,
            name="mesh-attach",
        )
        t.start()
        self._mesh_attach_thread = t
        if self.cluster is not None:
            self.cluster.join()
        # multi-process serving (docs/multiprocess.md): join the shared
        # public port only NOW — after the cluster join has completed —
        # so the kernel (reuseport) or the parent (fd-pass) never routes
        # a public connection to a child that cannot serve its shard
        # subset yet (readiness gating before the port is announced)
        if self.config.shared_bind:
            host, _, port = self.config.shared_bind.rpartition(":")
            self.http.add_shared_listener(host, int(port))
            self.logger.log(
                "shared public listener bound via SO_REUSEPORT on "
                f"{self.config.shared_bind}"
            )
        if self.config.fd_pass_socket:
            self.http.add_fd_listener(
                os.path.expanduser(self.config.fd_pass_socket)
            )
            self.logger.log(
                "adopting accept-and-pass connections from "
                f"{self.config.fd_pass_socket}"
            )
        self._schedule_anti_entropy()
        from pilosa_tpu.server.diagnostics import DiagnosticsCollector

        self.diagnostics = DiagnosticsCollector(self)
        self.api.diagnostics = self.diagnostics
        self.diagnostics.open()

    @staticmethod
    def _probe_device_backend(timeout_s: float, ttl_s: float = 0.0) -> bool:
        """Prove the backend this process will use initializes, in a
        FRESH subprocess (a wedged device transport hangs init forever,
        and a hang inside THIS process would poison every later jax
        call — backend init is process-global and uninterruptible). The
        child mirrors the parent's config-level platform pin: an env var
        alone can be swallowed by a site-installed plugin hook. The
        verdict is cached process-wide — backends are process-global, so
        one probe answers for every Server this process opens."""
        global _DEVICE_PROBE_OK
        if _DEVICE_PROBE_OK is not None:
            return _DEVICE_PROBE_OK
        import subprocess
        import sys

        import jax

        from pilosa_tpu.utils import probecache

        pin = jax.config.jax_platforms or ""
        cached = probecache.load(ttl_s, pin)
        if cached is not None and not cached["ok"]:
            # a persisted NEGATIVE verdict within its TTL answers in
            # <1 s — a known-wedged transport must not cost a fresh
            # 300 s probe on every boot (VERDICT #3b). A positive
            # verdict is never trusted across boots: the transport can
            # wedge between them, and skipping the probe would recreate
            # the unwatched first-jax-call hang this probe prevents.
            _DEVICE_PROBE_OK = False
            return False
        body = (
            f"import jax; jax.config.update('jax_platforms', {pin!r}); "
            "jax.devices()"
            if pin
            else "import jax; jax.devices()"
        )
        try:
            proc = subprocess.run(
                [sys.executable, "-c", body],
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                timeout=timeout_s,
            )
            _DEVICE_PROBE_OK = proc.returncode == 0
        except Exception:  # noqa: BLE001 — timeout, fork failure, ...
            # ANY probe failure means the device is unproven: report
            # False so the caller pins CPU. Letting an OSError escape
            # here would skip the pin and recreate the indefinite
            # first-jax-call hang this probe exists to prevent.
            _DEVICE_PROBE_OK = False
        probecache.store(_DEVICE_PROBE_OK, pin)
        return _DEVICE_PROBE_OK

    def _attach_mesh_when_ready(self) -> None:
        try:
            self._attach_mesh_inner()
        finally:
            self._mesh_ready.set()  # verdict landed (attached or host path)

    def _attach_mesh_inner(self) -> None:
        try:
            timeout_s = self.config.device_init_timeout
            if timeout_s > 0 and not self._probe_device_backend(
                timeout_s, self.config.device_probe_ttl
            ):
                # the accelerator cannot be trusted to init: pin THIS
                # process to the CPU backend before any jax call, or the
                # first query would hang indefinitely inside backend
                # init. Loud — this trades device speed for liveness
                # until restart.
                import jax

                jax.config.update("jax_platforms", "cpu")
                # degraded engine: every read runs on the vectorized
                # host fast path — a CPU-pinned process must not pay
                # jax dispatch per query (an explicit route-mode wins)
                self.api.executor.router.pin_host()
                self.logger.log(
                    "accelerator backend failed to initialize within "
                    f"{timeout_s:.0f}s — pinning this process to the CPU "
                    "backend (queries serve on the host fast path; "
                    "restart to retry the device)"
                )
            if not self.config.mesh_enabled:
                return  # probe/pin decided; nothing to attach
            ctx = self._make_mesh_context()
        except Exception as e:  # noqa: BLE001 — backend init is best-effort
            global _MESH_ATTACH_WARNED
            if not _MESH_ATTACH_WARNED:
                _MESH_ATTACH_WARNED = True
                self.logger.log(f"mesh attach failed (serving host path): {e}")
            return
        if not self._closed:
            self.api.attach_mesh(ctx)

    def _query_gate(self, wait: bool = True) -> bool:
        """Hold query/import dispatch off JAX until the device-probe
        verdict lands (ADVICE r5 medium): a query during the probe window
        would initialize the unpinned — possibly wedged — accelerator
        backend in-process, hang uninterruptibly, and hold JAX's
        process-global init lock so the post-probe CPU pin could never
        recover. Keyed on the ``_mesh_ready`` event (set when the attach
        thread finishes), which is unset from construction — so the gate
        also covers the open() window where the listener already serves
        but the attach thread hasn't been created yet. With ``wait``,
        blocks up to ``query_gate_wait`` for the verdict; past that the
        HTTP layer serves 503 + Retry-After. ``wait=False`` is for the
        internal fan-out route, whose caller's RPC timeout (30s) is
        shorter than the gate wait — it must fail fast and let the
        coordinator retry, not hang the RPC into a timeout.
        ``queries_gated`` counts every request that arrived inside the
        window."""
        if self._mesh_ready.is_set():
            return True
        self.stats.count("queries_gated")
        if not wait:
            return False
        return self._mesh_ready.wait(self.config.query_gate_wait)

    def wait_mesh(self, timeout: float | None = None) -> bool:
        """Block until the off-thread mesh attach finishes (tests and
        scripted drivers that assert on sharded execution right after
        open). True when the attach thread is done (attached or failed);
        False on timeout. No-op truth when mesh was disabled."""
        t = self._mesh_attach_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def _make_mesh_context(self):
        """Serving mesh: always over this process's LOCAL devices — even
        in a multi-host deployment. A global (cross-process) mesh program
        is a collective: every process must enter it in lockstep, and the
        HTTP query path is driven by whichever node a client happens to
        hit, so attaching a global mesh here would hang the first query
        in a DCN psum waiting for peers that never dispatch it. Cross-
        host queries therefore scatter-gather through parallel.cluster
        (each node reducing over its local mesh), while the global-mesh
        data plane (MeshContext(multihost=True) + MeshQueryEngine) is for
        symmetric SPMD drivers — every process running the same program —
        as in tests/test_multihost.py's two-process Count."""
        from pilosa_tpu.parallel.mesh import MeshContext

        return MeshContext.auto(words_axis=self.config.mesh_words_axis)

    def _schedule_anti_entropy(self) -> None:
        interval = self.config.anti_entropy_interval
        if interval <= 0 or self._closed:
            return

        def tick():
            try:
                if self.cluster is not None:
                    self.cluster.sync_holder()
            finally:
                self._schedule_anti_entropy()

        self._anti_entropy_timer = threading.Timer(interval, tick)
        self._anti_entropy_timer.daemon = True
        self._anti_entropy_timer.name = "anti-entropy"
        self._anti_entropy_timer.start()

    @property
    def port(self) -> int:
        """Actual bound port (useful when config requested :0)."""
        return self.http.server_address[1] if self.http else self.config.port

    @property
    def uri(self) -> str:
        return f"{self.config.scheme}://{self.config.host}:{self.port}"

    def close(self) -> None:
        self._closed = True
        # reap the attach thread (bounded — a wedged probe must not hang
        # shutdown): a daemon thread logging after close would otherwise
        # interleave with the embedding process's own output
        t = self._mesh_attach_thread
        if t is not None:
            t.join(timeout=10.0)
        if self.diagnostics is not None:
            self.diagnostics.close()
        if self._anti_entropy_timer is not None:
            self._anti_entropy_timer.cancel()
        if self.cluster is not None:
            self.cluster.close()
        self.api.scheduler.close()
        if self.profiler is not None:
            self.profiler.stop()
        if self.http is not None:
            self.http.saturation.stop()
            # flush the open workload spill segment before the listener
            # dies — a capture cut off mid-segment replays short
            self.http.workload.close()
            self.http.shutdown()
            self.http.server_close()
        self.stats.close()
        self.holder.close()
        if self.fs_fault_injector.armed:
            from pilosa_tpu.utils import durable

            durable.install_fs_hook(None)
        self.logger.close()
