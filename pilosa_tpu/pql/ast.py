"""PQL abstract syntax tree.

Reference: pql/ast.go (Query, Call, typed args map, *Condition for BSI
comparisons). A parsed query is a list of top-level ``Call``s; each call
has a name, keyword args (typed: int, str, bool, list, Condition,
datetime), positional scalar args, and positional child calls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# Condition ops for BSI comparisons (reference: pql.Condition tokens)
COND_OPS = ("==", "!=", "<", "<=", ">", ">=", "between")


@dataclass
class Condition:
    """A BSI comparison: ``field <op> value`` or ``lo < field < hi``."""

    op: str
    value: Any  # int, or [lo, hi] for "between"

    def __post_init__(self) -> None:
        if self.op not in COND_OPS:
            raise ValueError(f"bad condition op {self.op!r}")


@dataclass
class Call:
    name: str
    args: dict[str, Any] = field(default_factory=dict)
    children: list["Call"] = field(default_factory=list)
    pos_args: list[Any] = field(default_factory=list)

    def arg(self, key: str, default: Any = None) -> Any:
        return self.args.get(key, default)

    def condition(self) -> tuple[str, Condition] | None:
        """The (field, Condition) pair if this call carries one."""
        for k, v in self.args.items():
            if isinstance(v, Condition):
                return k, v
        return None

    def field_arg(self) -> tuple[str, Any] | None:
        """First (field, row) style arg — the key that isn't a reserved
        option name (reference: Call.FieldArg)."""
        reserved = {"from", "to", "field", "_timestamp"}
        for k, v in self.args.items():
            if k not in reserved and not isinstance(v, Condition):
                return k, v
        return None

    def __repr__(self) -> str:
        parts = [repr(c) for c in self.children]
        parts += [f"{v!r}" for v in self.pos_args]
        parts += [f"{k}={v!r}" for k, v in self.args.items()]
        return f"{self.name}({', '.join(parts)})"

    def to_pql(self) -> str:
        """Render back to parseable PQL text (used to forward single calls
        to peer nodes — reference ships protobuf-serialized Calls instead)."""
        parts = [c.to_pql() for c in self.children]
        parts += [_render_value(v) for v in self.pos_args]
        for k, v in self.args.items():
            if isinstance(v, Condition):
                if v.op == "between":
                    lo, hi = v.value
                    parts.append(f"{_render_value(lo)} <= {k} <= {_render_value(hi)}")
                else:
                    parts.append(f"{k} {v.op} {_render_value(v.value)}")
            else:
                parts.append(f"{k}={_render_value(v)}")
        return f"{self.name}({', '.join(parts)})"


def _render_value(v: Any) -> str:
    from datetime import datetime

    if isinstance(v, Call):
        return v.to_pql()
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, datetime):
        return v.strftime("%Y-%m-%dT%H:%M:%S")
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    if isinstance(v, list):
        return "[" + ", ".join(_render_value(x) for x in v) + "]"
    return str(v)
