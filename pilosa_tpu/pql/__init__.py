"""L2 query language: PQL parsing (reference: pql/ package)."""

from pilosa_tpu.pql.ast import Call, Condition
from pilosa_tpu.pql.parser import PQLError, coerce_timestamp, parse

__all__ = ["Call", "Condition", "parse", "PQLError", "coerce_timestamp"]
