"""PQL parser: query text → list of Call ASTs.

Reference: pql/pql.peg (compiled by pigeon into pql.peg.go). The grammar is
small, so a hand-written tokenizer + recursive-descent parser replaces the
PEG machinery; semantics follow the reference grammar:

    query      := call*
    call       := Name '(' args? ')'
    args       := arg (',' arg)*
    arg        := call                      (positional child)
                | Name '=' value            (keyword arg)
                | Name '=' call             (call-valued keyword arg)
                | Name COND value           (BSI condition, e.g. f > 5)
                | value COND Name COND value (between, e.g. 1 < f < 10)
                | Name '><' '[' v ',' v ']' (legacy between)
                | value                     (positional scalar)
    value      := int | float | string | bool | null | timestamp | list

Both ``Row(f > 5)`` (v1.3+) and ``Range(f > 5)`` (older) comparison forms
are accepted; the executor treats them identically.
"""

from __future__ import annotations

import re
from datetime import datetime
from typing import Any

from pilosa_tpu.pql.ast import Call, Condition

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<timestamp>\d{4}-\d{2}-\d{2}(?:T\d{2}:\d{2}(?::\d{2})?)?)
  | (?P<float>-?\d+\.\d+(?:[eE][+-]?\d+)?|-?\d+[eE][+-]?\d+)
  | (?P<int>-?\d+)
  | (?P<name>[A-Za-z_][A-Za-z0-9_-]*)
  | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<op><=|>=|==|!=|><|<|>|=)
  | (?P<punct>[(),\[\]])
    """,
    re.VERBOSE,
)

_BOOL_NULL = {"true": True, "false": False, "null": None}


class PQLError(ValueError):
    pass


class _Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: Any, pos: int):
        self.kind = kind
        self.value = value
        self.pos = pos

    def __repr__(self) -> str:
        return f"{self.kind}:{self.value!r}"


def _tokenize(text: str) -> list[_Token]:
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise PQLError(f"unexpected character {text[pos]!r} at {pos}")
        kind = m.lastgroup
        val = m.group()
        if kind != "ws":
            if kind == "int":
                tokens.append(_Token("int", int(val), pos))
            elif kind == "float":
                tokens.append(_Token("float", float(val), pos))
            elif kind == "string":
                tokens.append(_Token("string", _unquote(val), pos))
            elif kind == "timestamp":
                tokens.append(_Token("timestamp", _parse_ts(val), pos))
            else:
                tokens.append(_Token(kind, val, pos))
        pos = m.end()
    tokens.append(_Token("eof", None, pos))
    return tokens


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def _parse_ts(s: str) -> datetime:
    for fmt in ("%Y-%m-%dT%H:%M:%S", "%Y-%m-%dT%H:%M", "%Y-%m-%d"):
        try:
            return datetime.strptime(s, fmt)
        except ValueError:
            continue
    raise PQLError(f"bad timestamp {s!r}")


def coerce_timestamp(value) -> datetime | None:
    """Accept a timestamp arg in any form PQL clients send it: already a
    datetime (bare literal), or a quoted ISO string (the reference's
    grammar allows both ``from=2006-01-02T15:04`` and
    ``from="2006-01-02T15:04"``). None / non-timestamp strings → None."""
    if isinstance(value, datetime):
        return value
    if isinstance(value, str):
        try:
            return _parse_ts(value)
        except PQLError:
            return None
    return None


_COND_FROM_OP = {"<": "<", "<=": "<=", ">": ">", ">=": ">=", "==": "==", "!=": "!="}
# flip for the "value OP name" between-prefix form: 5 < f  means  f > 5
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}


class _Parser:
    def __init__(self, tokens: list[_Token]):
        self.tokens = tokens
        self.i = 0

    def peek(self, k: int = 0) -> _Token:
        return self.tokens[min(self.i + k, len(self.tokens) - 1)]

    def next(self) -> _Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def expect(self, kind: str, value: Any = None) -> _Token:
        t = self.next()
        if t.kind != kind or (value is not None and t.value != value):
            raise PQLError(
                f"expected {value or kind} at {t.pos}, got {t.value!r}"
            )
        return t

    # ------------------------------------------------------------- grammar
    def parse_query(self) -> list[Call]:
        calls = []
        while self.peek().kind != "eof":
            calls.append(self.parse_call())
        return calls

    def parse_call(self) -> Call:
        name = self.expect("name").value
        self.expect("punct", "(")
        call = Call(name)
        while True:
            t = self.peek()
            if t.kind == "punct" and t.value == ")":
                self.next()
                break
            self.parse_arg(call)
            t = self.peek()
            if t.kind == "punct" and t.value == ",":
                self.next()
            elif not (t.kind == "punct" and t.value == ")"):
                raise PQLError(f"expected ',' or ')' at {t.pos}, got {t.value!r}")
        return call

    def parse_arg(self, call: Call) -> None:
        t = self.peek()
        # positional child call:  Name '('
        if t.kind == "name" and self.peek(1).kind == "punct" and self.peek(1).value == "(":
            child_or_kw = self.parse_call()
            call.children.append(child_or_kw)
            return
        if t.kind == "name" and self.peek(1).kind == "op":
            name = self.next().value
            op = self.next().value
            if op == "=":
                self.parse_keyword_value(call, name)
            elif op == "><":
                # legacy between: f >< [lo, hi]
                vals = self.parse_value()
                if not isinstance(vals, list) or len(vals) != 2:
                    raise PQLError(f"'><' needs a two-element list at {t.pos}")
                call.args[name] = Condition("between", vals)
            else:
                call.args[name] = Condition(_COND_FROM_OP[op], self.parse_value())
            return
        # between prefix form:  value < name < value  (integers only —
        # BSI conditions are integer comparisons)
        if t.kind == "timestamp" and self.peek(1).kind == "op":
            raise PQLError(f"timestamps are not valid in conditions at {t.pos}")
        if t.kind in ("int", "float") and self.peek(1).kind == "op":
            lo = self.next().value
            op1 = self.next().value
            if self.peek().kind != "name":
                raise PQLError(f"expected field name at {self.peek().pos}")
            name = self.next().value
            op2t = self.next()
            if op2t.kind != "op" or op2t.value not in ("<", "<="):
                raise PQLError(f"bad between syntax at {op2t.pos}")
            hi = self.parse_value()
            if op1 not in ("<", "<="):
                raise PQLError(f"bad between syntax at {t.pos}")
            lo_adj = lo if op1 == "<=" else lo + 1
            hi_adj = hi if op2t.value == "<=" else hi - 1
            call.args[name] = Condition("between", [lo_adj, hi_adj])
            return
        # positional scalar
        call.pos_args.append(self.parse_value())

    def parse_keyword_value(self, call: Call, name: str) -> None:
        t = self.peek()
        if t.kind == "name" and t.value not in _BOOL_NULL:
            if self.peek(1).kind == "punct" and self.peek(1).value == "(":
                call.args[name] = self.parse_call()  # call-valued kwarg
                return
            # bare identifier value (e.g. field=fieldname)
            call.args[name] = self.next().value
            return
        call.args[name] = self.parse_value()

    def parse_value(self) -> Any:
        t = self.next()
        if t.kind in ("int", "float", "string", "timestamp"):
            return t.value
        if t.kind == "name":
            if t.value in _BOOL_NULL:
                return _BOOL_NULL[t.value]
            return t.value
        if t.kind == "punct" and t.value == "[":
            out = []
            while True:
                if self.peek().kind == "punct" and self.peek().value == "]":
                    self.next()
                    return out
                out.append(self.parse_value())
                if self.peek().kind == "punct" and self.peek().value == ",":
                    self.next()
        raise PQLError(f"unexpected token {t.value!r} at {t.pos}")


def parse(text: str) -> list[Call]:
    """Parse PQL text into a list of top-level calls (reference:
    pql.ParseString)."""
    return _Parser(_tokenize(text)).parse_query()
