from pilosa_tpu.cli import main

raise SystemExit(main())
