"""Device-mesh query execution: whole-index programs under one pjit.

Reference mapping (SURVEY.md §3 parallelism inventory): the reference's
only parallelism is shard scatter-gather over HTTP (executor.go mapReduce →
mapperLocal goroutines / mapperRemote HTTP). On a TPU pod the same shards
live as one stacked dense array across a ``jax.sharding.Mesh`` and the
reduce is an XLA collective over ICI, not an HTTP merge:

- mesh axis ``"shards"``  — data parallelism over the column space
  (shard s ↔ column range [s·SHARD_WIDTH, (s+1)·SHARD_WIDTH));
- mesh axis ``"words"``   — intra-shard parallelism over the packed word
  dimension: one logical row is a distributed bit-vector, the long-context
  / sequence-parallel analogue (a 10B-column row never materializes on one
  chip); cross-device ops on it are elementwise, only aggregations
  communicate (psum tree over ICI).

Arrays (row-major: rows lead so a row gather reads a contiguous [S, W]
plane — see executor.compile.stack_view_matrices for the measured why):
    row matrix   uint32[R, S, W]  sharded P(None, "shards", "words")
    row/filter   uint32[S, W]     sharded P("shards", "words")
    BSI slices   uint32[D, S, W]  sharded P(None, "shards", "words")

All counts psum over both axes; TopN does a words-then-shards psum of the
per-row count vector, then a replicated top_k (the reference's two-phase
merge collapses into one collective).
"""

from __future__ import annotations

import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu import ops
from pilosa_tpu.ops import bsi as bsi_ops
from pilosa_tpu.parallel import shard_map  # THE compat shim (jax 0.4/0.5+)

AXIS_SHARDS = "shards"
AXIS_WORDS = "words"
_BOTH = (AXIS_SHARDS, AXIS_WORDS)

# ----------------------------------------------------- mesh read coverage
# The serving-path SPMD surface (docs/spmd.md). The analyzer's parity
# rule diffs these literals against the executor's BITMAP_CALLS: every
# bitmap call type must either have a MeshQueryEngine program (its
# planner closure runs inside shard_map) or carry an explicit fallback
# annotation here — a silent gap would 500 (or worse, mis-reduce) the
# day the router sends that call type down the mesh path.
MESH_PROGRAMS = {
    "Row",
    "Range",
    "Union",
    "Intersect",
    "Difference",
    "Xor",
    "Not",
    "All",
}
# Aggregates served as mesh programs (psum/all_gather reduction trees —
# the multi-node merge transforms, intra-mesh and on-device).
MESH_AGGREGATES = {"Count", "Sum", "Min", "Max", "TopN", "GroupBy"}
# Host-fallback annotations: call types the mesh route hands back to the
# single-program device path (which still executes SPMD via the stacks'
# NamedSharding — GSPMD inserts the cross-device carries shard_map makes
# explicit).
#   Shift — the cross-word bit carry (ops.bitwise.shift_words rolls the
#   packed word axis) crosses device boundaries whenever the words axis
#   is split; expressing it under shard_map needs a words-axis
#   collective-permute chain that buys nothing for a metadata-rare call.
MESH_FALLBACK_CALLS = {"Shift"}


def mesh_supported(call) -> bool:
    """Can this call tree execute as explicit mesh (shard_map) programs?

    Walks the whole tree — a fallback-annotated call anywhere (e.g. a
    Shift inside an Intersect) sends the full query down the device
    path, since a mesh program cannot splice a non-SPMD subexpression.
    GroupBy's Rows() children and its aggregate=Sum() argument are row
    universes / aggregate specs, not bitmap subtrees — only their own
    filter children matter."""
    name = call.name
    if name == "Options":
        return all(mesh_supported(ch) for ch in call.children)
    if name in MESH_FALLBACK_CALLS:
        return False
    if name == "GroupBy":
        filt = call.arg("filter")
        if filt is not None and hasattr(filt, "name") and not mesh_supported(filt):
            return False
        return all(
            ch.name == "Rows" or mesh_supported(ch) for ch in call.children
        )
    if name in MESH_PROGRAMS or name in MESH_AGGREGATES:
        return all(mesh_supported(ch) for ch in call.children)
    return False


def make_mesh(devices=None, words_axis: int = 1) -> Mesh:
    """2-D device mesh (shards × words). ``words_axis`` > 1 splits the
    packed word dimension across devices (for giant rows); defaults to 1
    so every device owns whole shards."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % words_axis:
        raise ValueError(f"{n} devices not divisible by words_axis={words_axis}")
    grid = np.array(devices).reshape(n // words_axis, words_axis)
    return Mesh(grid, (AXIS_SHARDS, AXIS_WORDS))


class MeshContext:
    """Serving-path device placement over a (shards × words) mesh.

    The executor's stacked field matrices are placed with a
    ``NamedSharding`` so every compiled query program runs SPMD across
    the mesh: elementwise bitwise ops stay local to each device's shard
    slice, and the Count/TopN/Sum reductions become XLA all-reduces over
    ICI (the reference's executor.go mapReduce HTTP merge, collapsed
    into collectives). Single-device processes use no context (None) and
    keep plain device arrays.
    """

    def __init__(self, mesh: Mesh, multihost: bool = False):
        self.mesh = mesh
        # multihost: the mesh spans >1 process. Host arrays are then
        # placed with jax.make_array_from_process_local_data — each
        # process contributes ITS addressable slice of the global array
        # (its owned shards), so a psum over the mesh is a GLOBAL
        # reduction with no HTTP merge. Requires every process to run the
        # same program in lockstep (jax.distributed SPMD contract).
        self.multihost = multihost

    @classmethod
    def auto(cls, words_axis: int = 1, devices=None) -> "MeshContext | None":
        """A context over all LOCAL devices, or None when only one device
        is visible (the sharded and unsharded programs are identical
        there — skip the placement overhead). Local, not global: the
        serving stack places host numpy arrays with jax.device_put, which
        requires every mesh device to be addressable by this process; the
        cross-host data plane goes through parallel.cluster scatter-gather
        (and multihost.make_multihost_mesh for explicit pod meshes)."""
        devices = list(devices if devices is not None else jax.local_devices())
        if len(devices) <= 1:
            return None
        return cls(make_mesh(devices, words_axis=words_axis))

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def _spec(self, n_shards: int, n_words: int, lead_dims: int) -> P:
        """Placement rule: shard the S axis over the mesh when it divides
        evenly (the data-parallel layout — whole shards per device);
        otherwise shard the packed word axis over ALL devices (always a
        power of two, so any shard count — even S=1 — still uses the full
        mesh); tiny odd shapes replicate. ``jax.device_put`` requires
        exact divisibility, hence the explicit rule instead of padding.
        ``lead_dims`` is the number of leading (row) dims BEFORE the shard
        axis — row-major stacks are [R, S, W], so the shards axis sits at
        position ``lead_dims``."""
        shard_rows = self.mesh.shape[AXIS_SHARDS]
        lead = (None,) * lead_dims
        if n_shards % shard_rows == 0 and n_words % self.mesh.shape[AXIS_WORDS] == 0:
            return P(*lead, AXIS_SHARDS, AXIS_WORDS)
        if n_words % self.n_devices == 0:
            return P(*lead, None, (AXIS_SHARDS, AXIS_WORDS))
        return P()

    def _check_uniform_s(self, s: int) -> None:
        """Global shape is ``s × process_count``, which is only coherent
        when every process contributes the SAME shard count — topology
        does not guarantee that (5 shards over 2 hosts), and a mismatch
        would hang the next collective with no diagnostic. Unconditional
        (never cached): _place is itself collective under the lockstep
        contract, and a per-value cache would desynchronize the group the
        first time one process's S diverges (the cached side would skip
        the allgather the other side enters)."""
        from jax.experimental import multihost_utils

        counts = np.asarray(multihost_utils.process_allgather(np.int64(s)))
        if not (counts == s).all():
            raise ValueError(
                f"multi-host placement needs a uniform per-process shard "
                f"count; got {counts.tolist()} — pad every process to the "
                "same S (empty shards are all-zero rows)"
            )

    def _place(self, arr, lead_dims: int):
        s = arr.shape[lead_dims]
        w = arr.shape[-1]
        if self.multihost:
            n_proc = jax.process_count()
            self._check_uniform_s(s)
            s_global = s * n_proc
            spec = self._spec(s_global, w, lead_dims)
            if len(spec) <= lead_dims or spec[lead_dims] != AXIS_SHARDS:
                raise ValueError(
                    f"multi-host placement needs the shards axis sharded: "
                    f"global S={s_global} not divisible by mesh "
                    f"{self.mesh.shape[AXIS_SHARDS]} shard rows"
                )
            global_shape = (
                arr.shape[:lead_dims] + (s_global,) + arr.shape[lead_dims + 1 :]
            )
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec), arr, global_shape
            )
        return jax.device_put(arr, NamedSharding(self.mesh, self._spec(s, w, lead_dims)))

    def place_stack(self, stacked):
        """uint32[R, S, W] (or [D, S, W] BSI block) → sharded device array.
        Multi-host: S is this process's shard count; the global array
        concatenates every process's slice along S."""
        return self._place(stacked, 1)

    def place_rows(self, arr):
        """uint32[S, W] → sharded device array."""
        return self._place(arr, 0)

    def place_block(self, arr):
        """Compressed container payload stores (tiered residency:
        sparse [H, K] id lists, run [H, K, 2] interval lists) → mesh-
        placed REPLICATED arrays.  Payload ids live in the stacked
        plane's global position space, so there is no [S, W] plane axis
        to shard; replication keeps the single-program SPMD path working
        — the decoded planes the query programs build from these blocks
        merge with sharded dense stacks under GSPMD as usual."""
        if self.multihost:
            # replication requires identical data on every process, but
            # container payloads are packed from process-local fragments
            # — the tiered layer disables itself on multi-host meshes
            # (StackCache.residency_mode), so reaching here is a bug
            raise ValueError(
                "compressed container stores cannot be placed on a "
                "multi-host mesh (process-local payloads are not "
                "replicable); over-budget fields use the slot path there"
            )
        return jax.device_put(arr, NamedSharding(self.mesh, P()))


class MeshQueryEngine:
    """Compiles and caches sharded query programs over a fixed mesh.

    Two program families live here:

    - the concrete demo/bench programs (count_and, topn, bsi_sum,
      tanimoto/cosine, ingest_and_aggregate) — fixed signatures, used by
      dryrun_multichip, the examples and the multichip bench;
    - the serving-path program BUILDERS (bitmap_tree, count_tree,
      topn_tree, sum_tree, minmax_tree, groupby_*_tree, …): each takes a
      query-compiler planner closure and wraps it in ``shard_map`` over
      this mesh, turning the whole PQL read call into one SPMD program
      whose reduction is a psum tree over ICI (words — the minor/fast
      axis — first, then shards). The executor caches the built
      programs per structural key and AOT-compiles through
      QueryCompiler.call_program like every other program.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._aot: set[tuple] = set()
        # observability (/debug/vars meshExecution): program builds and
        # per-program-family call counts; a plain dict under a lock —
        # executor threads increment concurrently
        self._stats_lock = threading.Lock()
        self.programs_built = 0
        self.calls: dict[str, int] = {}
        self.fallbacks = 0

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    # ------------------------------------------------- placement algebra
    def spec_mode(self, n_shards: int, n_words: int) -> str | None:
        """How a [.., S, W] stack maps onto this mesh — the SAME rule as
        MeshContext._spec, so the specs a program compiles against match
        the placement the stack cache already gave its arrays:

        - "grid":  S divides the shards axis and W the words axis —
          whole shard slices per device row (data parallel);
        - "words": W divides the full device count — the packed word
          axis spans every device (a 1-shard query still uses the whole
          mesh);
        - None:    tiny odd shapes replicate; no mesh program (the
          device path serves them — psum over replicated data would
          multiply by the axis size).
        """
        if (
            n_shards % self.mesh.shape[AXIS_SHARDS] == 0
            and n_words % self.mesh.shape[AXIS_WORDS] == 0
        ):
            return "grid"
        if n_words % self.n_devices == 0:
            return "words"
        return None

    def block_shape(self, n_shards: int, n_words: int, mode: str) -> tuple[int, int]:
        """Per-device (S_local, W_local) block of an [S, W] plane — what
        planner closures see inside shard_map (zero leaves must be
        block-shaped, not global)."""
        if mode == "grid":
            return (
                n_shards // self.mesh.shape[AXIS_SHARDS],
                n_words // self.mesh.shape[AXIS_WORDS],
            )
        return (n_shards, n_words // self.n_devices)

    def _arr_spec(self, lead: int, mode: str) -> P:
        """Spec for an array with ``lead`` unsharded leading dims before
        its [S, W] plane (stacks are [R, S, W] ⇒ lead=1)."""
        lead_none = (None,) * lead
        if mode == "grid":
            return P(*lead_none, AXIS_SHARDS, AXIS_WORDS)
        return P(*lead_none, None, _BOTH)

    def row_spec(self, mode: str) -> P:
        return self._arr_spec(0, mode)

    @staticmethod
    def _psum_both(v):
        """The cross-chip reduction tree: words (minor/ICI) hop first,
        then shards — the multi-node merge transforms' order, intra-mesh."""
        return jax.lax.psum(jax.lax.psum(v, AXIS_WORDS), AXIS_SHARDS)

    def _spmd(self, local, in_specs, out_specs, check_rep: bool = True):
        prog = jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_rep=check_rep,
            )
        )
        with self._stats_lock:
            self.programs_built += 1
        return prog

    def note_call(self, name: str) -> None:
        with self._stats_lock:
            self.calls[name] = self.calls.get(name, 0) + 1

    def note_fallback(self) -> None:
        with self._stats_lock:
            self.fallbacks += 1

    def snapshot(self) -> dict:
        """Live view for /debug/vars (meshExecution)."""
        with self._stats_lock:
            calls = dict(self.calls)
            built, fallbacks = self.programs_built, self.fallbacks
        return {
            "devices": self.n_devices,
            "meshShape": {
                AXIS_SHARDS: int(self.mesh.shape[AXIS_SHARDS]),
                AXIS_WORDS: int(self.mesh.shape[AXIS_WORDS]),
            },
            "programsBuilt": built,
            "calls": calls,
            "fallbacks": fallbacks,
        }

    # --------------------------------------- serving-path program builders
    # Each builder closes over a planner closure ``run(arrays, scalars) →
    # uint32[S_local, W_local]`` (executor/compile.py plans it with this
    # mesh's block shape) and returns a jitted shard_map program. The
    # executor caches them per structural key; shapes retrace via jit.

    def bitmap_tree(self, run, mode: str):
        """(arrays [*,S,W]×N, scalars) → sharded uint32[S, W] — the whole
        bitmap call tree, elementwise per device block (no collectives)."""

        def local(arrays, scalars):
            return run(arrays, scalars)

        return self._spmd(
            local,
            (self._arr_spec(1, mode), P()),
            self.row_spec(mode),
        )

    def count_tree(self, run, mode: str):
        """(arrays, scalars) → replicated int64 count (psum tree)."""

        def local(arrays, scalars):
            words = run(arrays, scalars)
            return self._psum_both(
                jnp.sum(ops.popcount_rows(words).astype(jnp.int64))
            )

        return self._spmd(local, (self._arr_spec(1, mode), P()), P())

    def topn_tree(self, mode: str, filtered: bool, ids: bool, frun=None):
        """Per-row global counts int64[R] (or [K] for ids=), replicated:
        local masked popcounts, psum over words-then-shards. The filter
        expression (when present) computes INSIDE the program from its
        own planner closure — never materialized between dispatches."""

        def row_counts(matrix, filt):
            m = matrix & filt[None] if filt is not None else matrix
            return jnp.sum(ops.popcount_rows(m).astype(jnp.int64), axis=1)

        spec3 = self._arr_spec(1, mode)
        if ids and filtered:

            def local(matrix, row_ids, farrays, fscalars):
                g = jnp.take(matrix, row_ids, axis=0, mode="fill", fill_value=0)
                return self._psum_both(row_counts(g, frun(farrays, fscalars)))

            return self._spmd(
                local, (spec3, P(), spec3, P()), P()
            )
        if ids:

            def local(matrix, row_ids):
                g = jnp.take(matrix, row_ids, axis=0, mode="fill", fill_value=0)
                return self._psum_both(row_counts(g, None))

            return self._spmd(local, (spec3, P()), P())
        if filtered:

            def local(matrix, farrays, fscalars):
                return self._psum_both(
                    row_counts(matrix, frun(farrays, fscalars))
                )

            return self._spmd(local, (spec3, spec3, P()), P())

        def local(matrix):
            return self._psum_both(row_counts(matrix, None))

        return self._spmd(local, (spec3,), P())

    def sum_tree(self, sum_fn, mode: str, frun=None):
        """BSI Sum: (slices [D,S,W], filter) → (pos[D], neg[D], n),
        replicated — ``sum_fn`` is Executor._sum_fn, THE one reduction
        body (host/device/mesh stay in sync by construction)."""
        spec3 = self._arr_spec(1, mode)
        if frun is not None:

            def local(slices, farrays, fscalars):
                pos, neg, n = sum_fn(slices, frun(farrays, fscalars))
                return (
                    self._psum_both(pos),
                    self._psum_both(neg),
                    self._psum_both(n),
                )

            return self._spmd(
                local, (spec3, spec3, P()), (P(), P(), P())
            )

        def local(slices, filt):
            pos, neg, n = sum_fn(slices, filt)
            return (
                self._psum_both(pos),
                self._psum_both(neg),
                self._psum_both(n),
            )

        return self._spmd(
            local, (spec3, self.row_spec(mode)), (P(), P(), P())
        )

    def grouped_sum_tree(self, sum_fn, mode: str):
        """(slices [D,S,W], masks [G,S,W]) → (pos[G,D], neg[G,D], n[G])
        replicated — GroupBy's aggregate=Sum under the same psum tree."""
        spec3 = self._arr_spec(1, mode)

        def local(slices, masks):
            pos, neg, n = jax.vmap(sum_fn, in_axes=(None, 0))(slices, masks)
            return (
                self._psum_both(pos),
                self._psum_both(neg),
                self._psum_both(n),
            )

        return self._spmd(local, (spec3, spec3), (P(), P(), P()))

    def minmax_tree(self, want_max: bool, mode: str, frun=None):
        """BSI Min/Max: per-device per-shard extremes, all-gathered to a
        replicated partial list the executor's finish() merges exactly
        like per-shard device partials (min/max-with-count merges
        associatively over disjoint column blocks).

        check_rep=False: all_gather's replication isn't statically
        inferred on the pinned jax — the gather of every block IS full
        replication, the checker just can't prove it."""
        spec3 = self._arr_spec(1, mode)

        def gather_all(v):
            v = jax.lax.all_gather(v, AXIS_WORDS).reshape(-1)
            return jax.lax.all_gather(v, AXIS_SHARDS).reshape(-1)

        def body(slices, filt):
            vals, counts = jax.vmap(
                lambda ss, ff: bsi_ops.min_max(ss, ff, want_max=want_max),
                in_axes=(1, 0),
            )(slices, filt)
            return gather_all(vals), gather_all(counts)

        if frun is not None:

            def local(slices, farrays, fscalars):
                return body(slices, frun(farrays, fscalars))

            return self._spmd(
                local, (spec3, spec3, P()), (P(), P()), check_rep=False
            )

        def local(slices, filt):
            return body(slices, filt)

        return self._spmd(
            local,
            (spec3, self.row_spec(mode)),
            (P(), P()),
            check_rep=False,
        )

    def groupby_counts_tree(self, mode: str):
        """(masks [G,S,W], matrix [R,S,W], rows [K]) → int64[G,K]
        replicated — the level-synchronous GroupBy count pass with the
        per-level merge as one psum tree (executor._gb_counts, intra-mesh)."""
        spec3 = self._arr_spec(1, mode)

        def local(masks, matrix, rows):
            gathered = jnp.take(matrix, rows, axis=0, mode="fill", fill_value=0)
            per_row = lambda rm: jnp.sum(
                ops.popcount_rows(masks & rm[None]).astype(jnp.int64), axis=1
            )
            return self._psum_both(jax.lax.map(per_row, gathered).T)

        return self._spmd(local, (spec3, spec3, P()), P())

    def groupby_masks_tree(self, mode: str):
        """(masks, matrix, g_idx, row_sel) → sharded [P,S,W] surviving
        group masks — pure elementwise gather+AND, no collectives."""
        spec3 = self._arr_spec(1, mode)

        def local(masks, matrix, g_idx, row_sel):
            sel = jnp.take(masks, g_idx, axis=0)
            rows = jnp.take(matrix, row_sel, axis=0, mode="fill", fill_value=0)
            return sel & rows

        return self._spmd(local, (spec3, spec3, P(), P()), spec3)

    def _call(self, name: str, prog, *args):
        """Explicit AOT compile per (program, shapes) before the first
        call — jit's lazy compile-on-call path is pathologically slow on
        remote/tunneled accelerators and skips the persistent compile
        cache (see executor.compile.QueryCompiler.call_program, where
        this was measured: the subsequent concrete prog() call reuses
        the AOT-compiled executable rather than recompiling — measured
        ~0 s after a sub-second lower().compile() for a program whose
        lazy path took a minute). Static trailing args (e.g. top-k's k,
        a plain int or numpy scalar — NOT an ndarray) pass through to
        lower() as-is."""
        shapes = tuple(
            jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if isinstance(x, (np.ndarray, jax.Array))
            else x
            for x in args
        )
        sig = (name,) + tuple(
            (s.shape, s.dtype, s.sharding)
            if isinstance(s, jax.ShapeDtypeStruct)
            else s
            for s in shapes
        )
        if sig not in self._aot:
            prog.lower(*shapes).compile()
            self._aot.add(sig)
        return prog(*args)

    # ------------------------------------------------------------ placement
    def spec_matrix(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(None, AXIS_SHARDS, AXIS_WORDS))

    def spec_row(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(AXIS_SHARDS, AXIS_WORDS))

    def place_matrix(self, stacked: np.ndarray):
        """uint32[R, S, W] (row-major) → device, sharded (shards, words)."""
        return jax.device_put(stacked, self.spec_matrix())

    def place_row(self, stacked: np.ndarray):
        """uint32[S, W] → device."""
        return jax.device_put(stacked, self.spec_row())

    # ------------------------------------------------------------- programs
    def count_and(self, a, b):
        return self._call("count_and", self._count_and_prog, a, b)

    def topn(self, matrix, filt, k: int):
        return self._call("topn", self._topn_prog, matrix, filt, k)

    def bsi_sum(self, slices, filt):
        return self._call("bsi_sum", self._bsi_sum_prog, slices, filt)

    def ingest_and_aggregate(self, matrix, delta, filt):
        return self._call(
            "ingest_and_aggregate", self._ingest_prog, matrix, delta, filt
        )

    @functools.cached_property
    def _count_and_prog(self):
        @jax.jit
        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=P(),
        )
        def prog(a, b):
            local = ops.count_and(a, b)  # staged i32→i64 (see ops.popcount)
            return jax.lax.psum(jax.lax.psum(local, AXIS_WORDS), AXIS_SHARDS)

        return prog

    @functools.cached_property
    def _topn_prog(self):
        """(matrix [R,S,W], filt [S,W]) → per-row global counts int64[R]
        (psum over both axes; top_k happens on the replicated vector)."""

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=P(),
        )
        def counts_prog(matrix, filt):
            # [R, S_local] i32; i64 only past this point (layout: count_and)
            per = ops.popcount_rows(matrix & filt[None])
            local = jnp.sum(per.astype(jnp.int64), axis=1)
            return jax.lax.psum(jax.lax.psum(local, AXIS_WORDS), AXIS_SHARDS)

        @functools.partial(jax.jit, static_argnums=(2,))
        def prog(matrix, filt, k: int):
            counts = counts_prog(matrix, filt)
            k = min(k, counts.shape[0])
            vals, ids = jax.lax.top_k(counts, k)
            return vals, ids.astype(jnp.int32)

        return prog

    @functools.cached_property
    def _tanimoto_prog(self):
        """(matrix [R,S,W], query [S,W]) → (scores f32[k], ids i32[k]) —
        BASELINE config 5 (chemical-similarity search) as ONE SPMD
        program: per-device partial |a∩q| and |a| popcounts, psum over
        words-then-shards (the words hop rides the fast/ICI minor axis),
        Tanimoto on the replicated vectors, top_k replicated."""

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=(P(), P(), P()),
        )
        def counts_prog(matrix, query):
            inter = jnp.sum(
                ops.popcount_rows(matrix & query[None]).astype(jnp.int64),
                axis=1,
            )
            row_pop = jnp.sum(
                ops.popcount_rows(matrix).astype(jnp.int64), axis=1
            )
            q_pop = jnp.sum(ops.popcount_rows(query).astype(jnp.int64))
            red = lambda v: jax.lax.psum(
                jax.lax.psum(v, AXIS_WORDS), AXIS_SHARDS
            )
            return red(inter), red(row_pop), red(q_pop)

        @functools.partial(jax.jit, static_argnums=(2,))
        def prog(matrix, query, k: int):
            inter, row_pop, q_pop = counts_prog(matrix, query)
            inter = inter.astype(jnp.float32)
            union = row_pop.astype(jnp.float32) + q_pop.astype(jnp.float32) - inter
            scores = jnp.where(union > 0, inter / union, 0.0)
            k = min(k, scores.shape[0])
            vals, ids = jax.lax.top_k(scores, k)
            return vals, ids.astype(jnp.int32)

        return prog

    def tanimoto(self, matrix, query, k: int):
        return self._call("tanimoto", self._tanimoto_prog, matrix, query, k)

    @functools.cached_property
    def _cosine_prog(self):
        """(matrix [R,S,W], query [S,W]) → (scores f32[k], ids i32[k]) —
        the cosine twin of the Tanimoto search: same psum tree, scores
        |a∩q| / sqrt(|a|·|q|) on the replicated count vectors."""

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=(P(), P(), P()),
        )
        def counts_prog(matrix, query):
            inter = jnp.sum(
                ops.popcount_rows(matrix & query[None]).astype(jnp.int64),
                axis=1,
            )
            row_pop = jnp.sum(
                ops.popcount_rows(matrix).astype(jnp.int64), axis=1
            )
            q_pop = jnp.sum(ops.popcount_rows(query).astype(jnp.int64))
            return (
                self._psum_both(inter),
                self._psum_both(row_pop),
                self._psum_both(q_pop),
            )

        @functools.partial(jax.jit, static_argnums=(2,))
        def prog(matrix, query, k: int):
            inter, row_pop, q_pop = counts_prog(matrix, query)
            denom = jnp.sqrt(
                row_pop.astype(jnp.float32) * q_pop.astype(jnp.float32)
            )
            scores = jnp.where(
                denom > 0, inter.astype(jnp.float32) / denom, 0.0
            )
            k = min(k, scores.shape[0])
            vals, ids = jax.lax.top_k(scores, k)
            return vals, ids.astype(jnp.int32)

        return prog

    def cosine(self, matrix, query, k: int):
        return self._call("cosine", self._cosine_prog, matrix, query, k)

    # ------------------------------------------- all-pairs (MXU) programs
    # The paper's matmul-shaped workload (arXiv 2112.09017): pairwise
    # similarity between two fingerprint sets as ONE distributed matmul.
    # Bits unpack to {0,1} bf16 per device block, the per-block dot
    # rides the MXU, and the contraction over the split word axis is a
    # psum — rows of ``a`` stay sharded over the shards axis, so the
    # [N, M] score matrix never replicates.

    def place_allpairs(self, a: np.ndarray, b: np.ndarray):
        """(a uint32[N, W], b uint32[M, W]) → placed device pair: a rows
        sharded over the shards axis (words over words), b replicated
        over shards (every device row scores its a-slice against all of
        b). N must divide the shards axis and W the words axis."""
        if a.shape[0] % self.mesh.shape[AXIS_SHARDS]:
            raise ValueError(
                f"N={a.shape[0]} rows not divisible by the shards axis "
                f"({self.mesh.shape[AXIS_SHARDS]})"
            )
        if a.shape[-1] % self.mesh.shape[AXIS_WORDS]:
            raise ValueError(
                f"W={a.shape[-1]} words not divisible by the words axis "
                f"({self.mesh.shape[AXIS_WORDS]})"
            )
        a_dev = jax.device_put(
            a, NamedSharding(self.mesh, P(AXIS_SHARDS, AXIS_WORDS))
        )
        b_dev = jax.device_put(
            b, NamedSharding(self.mesh, P(None, AXIS_WORDS))
        )
        return a_dev, b_dev

    def _pairwise_prog(self, kind: str):
        from pilosa_tpu.ops.similarity import _unpack_bits_bf16

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(AXIS_SHARDS, AXIS_WORDS), P(None, AXIS_WORDS)),
            out_specs=P(AXIS_SHARDS, None),
        )
        def prog(a, b):
            a_bits = _unpack_bits_bf16(a)
            b_bits = _unpack_bits_bf16(b)
            inter = jax.lax.psum(
                jnp.dot(a_bits, b_bits.T, preferred_element_type=jnp.float32),
                AXIS_WORDS,
            )
            a_pop = jax.lax.psum(
                ops.popcount_rows(a).astype(jnp.float32), AXIS_WORDS
            )
            b_pop = jax.lax.psum(
                ops.popcount_rows(b).astype(jnp.float32), AXIS_WORDS
            )
            if kind == "tanimoto":
                union = a_pop[:, None] + b_pop[None, :] - inter
                return jnp.where(union > 0, inter / union, 0.0)
            denom = jnp.sqrt(a_pop[:, None] * b_pop[None, :])
            return jnp.where(denom > 0, inter / denom, 0.0)

        return jax.jit(prog)

    @functools.cached_property
    def _pairwise_tanimoto_prog(self):
        return self._pairwise_prog("tanimoto")

    @functools.cached_property
    def _pairwise_cosine_prog(self):
        return self._pairwise_prog("cosine")

    def pairwise_tanimoto(self, a, b):
        """All-pairs Tanimoto over a placed pair → f32[N, M], rows
        sharded (ops.similarity.tanimoto_matrix, distributed)."""
        return self._call(
            "pairwise_tanimoto", self._pairwise_tanimoto_prog, a, b
        )

    def pairwise_cosine(self, a, b):
        return self._call("pairwise_cosine", self._pairwise_cosine_prog, a, b)

    @functools.cached_property
    def _bsi_sum_prog(self):
        """(slices [D,S,W], filt [S,W]) → (sum int64, count int64)."""

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=(P(), P()),
        )
        def prog(slices, filt):
            exists = slices[bsi_ops.EXISTS_ROW]
            sign = slices[bsi_ops.SIGN_ROW]
            mag = slices[bsi_ops.OFFSET_ROW :]
            pos = (exists & ~sign & filt)[None]
            neg = (exists & sign & filt)[None]
            depth = mag.shape[0]
            weights = jnp.asarray([1 << k for k in range(depth)], dtype=jnp.int64)
            pc = jnp.sum(ops.popcount_rows(mag & pos).astype(jnp.int64), axis=1)
            nc = jnp.sum(ops.popcount_rows(mag & neg).astype(jnp.int64), axis=1)
            local_sum = jnp.sum((pc - nc) * weights)
            local_n = ops.popcount(exists & filt)
            total = jax.lax.psum(jax.lax.psum(local_sum, AXIS_WORDS), AXIS_SHARDS)
            n = jax.lax.psum(jax.lax.psum(local_n, AXIS_WORDS), AXIS_SHARDS)
            return total, n

        return prog

    @functools.cached_property
    def _ingest_prog(self):
        """The full "step": apply a packed write delta to the row matrix
        (device-side ingest, the donated-buffer mutation path) then compute
        the standing aggregates — one compiled program, zero host round
        trips (reference analogue: fragment.bulkImport + executor pass).

        (matrix [R,S,W], delta [R,S,W], filt [S,W])
            → (new_matrix, per-row counts int64[R], total int64)
        """

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(
                P(None, AXIS_SHARDS, AXIS_WORDS),
                P(None, AXIS_SHARDS, AXIS_WORDS),
                P(AXIS_SHARDS, AXIS_WORDS),
            ),
            out_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(), P()),
        )
        def prog(matrix, delta, filt):
            new_matrix = matrix | delta
            local_counts = jnp.sum(
                ops.popcount_rows(new_matrix & filt[None]).astype(jnp.int64),
                axis=1,
            )
            counts = jax.lax.psum(
                jax.lax.psum(local_counts, AXIS_WORDS), AXIS_SHARDS
            )
            total = jnp.sum(counts)
            return new_matrix, counts, total

        return jax.jit(prog, donate_argnums=(0,))


def stack_field_matrices(field, shards: list[int]) -> np.ndarray:
    """Stack a field's standard-view fragment matrices → uint32[R, S, W]
    (host-side, row-major; rows padded to the max across shards)."""
    from pilosa_tpu.core import VIEW_STANDARD
    from pilosa_tpu.executor.compile import stack_view_matrices

    return stack_view_matrices(field.view(VIEW_STANDARD), shards)[0]
