"""Device-mesh query execution: whole-index programs under one pjit.

Reference mapping (SURVEY.md §3 parallelism inventory): the reference's
only parallelism is shard scatter-gather over HTTP (executor.go mapReduce →
mapperLocal goroutines / mapperRemote HTTP). On a TPU pod the same shards
live as one stacked dense array across a ``jax.sharding.Mesh`` and the
reduce is an XLA collective over ICI, not an HTTP merge:

- mesh axis ``"shards"``  — data parallelism over the column space
  (shard s ↔ column range [s·SHARD_WIDTH, (s+1)·SHARD_WIDTH));
- mesh axis ``"words"``   — intra-shard parallelism over the packed word
  dimension: one logical row is a distributed bit-vector, the long-context
  / sequence-parallel analogue (a 10B-column row never materializes on one
  chip); cross-device ops on it are elementwise, only aggregations
  communicate (psum tree over ICI).

Arrays (row-major: rows lead so a row gather reads a contiguous [S, W]
plane — see executor.compile.stack_view_matrices for the measured why):
    row matrix   uint32[R, S, W]  sharded P(None, "shards", "words")
    row/filter   uint32[S, W]     sharded P("shards", "words")
    BSI slices   uint32[D, S, W]  sharded P(None, "shards", "words")

All counts psum over both axes; TopN does a words-then-shards psum of the
per-row count vector, then a replicated top_k (the reference's two-phase
merge collapses into one collective).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu import ops
from pilosa_tpu.ops import bsi as bsi_ops

AXIS_SHARDS = "shards"
AXIS_WORDS = "words"
_BOTH = (AXIS_SHARDS, AXIS_WORDS)


def make_mesh(devices=None, words_axis: int = 1) -> Mesh:
    """2-D device mesh (shards × words). ``words_axis`` > 1 splits the
    packed word dimension across devices (for giant rows); defaults to 1
    so every device owns whole shards."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % words_axis:
        raise ValueError(f"{n} devices not divisible by words_axis={words_axis}")
    grid = np.array(devices).reshape(n // words_axis, words_axis)
    return Mesh(grid, (AXIS_SHARDS, AXIS_WORDS))


class MeshContext:
    """Serving-path device placement over a (shards × words) mesh.

    The executor's stacked field matrices are placed with a
    ``NamedSharding`` so every compiled query program runs SPMD across
    the mesh: elementwise bitwise ops stay local to each device's shard
    slice, and the Count/TopN/Sum reductions become XLA all-reduces over
    ICI (the reference's executor.go mapReduce HTTP merge, collapsed
    into collectives). Single-device processes use no context (None) and
    keep plain device arrays.
    """

    def __init__(self, mesh: Mesh, multihost: bool = False):
        self.mesh = mesh
        # multihost: the mesh spans >1 process. Host arrays are then
        # placed with jax.make_array_from_process_local_data — each
        # process contributes ITS addressable slice of the global array
        # (its owned shards), so a psum over the mesh is a GLOBAL
        # reduction with no HTTP merge. Requires every process to run the
        # same program in lockstep (jax.distributed SPMD contract).
        self.multihost = multihost

    @classmethod
    def auto(cls, words_axis: int = 1, devices=None) -> "MeshContext | None":
        """A context over all LOCAL devices, or None when only one device
        is visible (the sharded and unsharded programs are identical
        there — skip the placement overhead). Local, not global: the
        serving stack places host numpy arrays with jax.device_put, which
        requires every mesh device to be addressable by this process; the
        cross-host data plane goes through parallel.cluster scatter-gather
        (and multihost.make_multihost_mesh for explicit pod meshes)."""
        devices = list(devices if devices is not None else jax.local_devices())
        if len(devices) <= 1:
            return None
        return cls(make_mesh(devices, words_axis=words_axis))

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def _spec(self, n_shards: int, n_words: int, lead_dims: int) -> P:
        """Placement rule: shard the S axis over the mesh when it divides
        evenly (the data-parallel layout — whole shards per device);
        otherwise shard the packed word axis over ALL devices (always a
        power of two, so any shard count — even S=1 — still uses the full
        mesh); tiny odd shapes replicate. ``jax.device_put`` requires
        exact divisibility, hence the explicit rule instead of padding.
        ``lead_dims`` is the number of leading (row) dims BEFORE the shard
        axis — row-major stacks are [R, S, W], so the shards axis sits at
        position ``lead_dims``."""
        shard_rows = self.mesh.shape[AXIS_SHARDS]
        lead = (None,) * lead_dims
        if n_shards % shard_rows == 0 and n_words % self.mesh.shape[AXIS_WORDS] == 0:
            return P(*lead, AXIS_SHARDS, AXIS_WORDS)
        if n_words % self.n_devices == 0:
            return P(*lead, None, (AXIS_SHARDS, AXIS_WORDS))
        return P()

    def _check_uniform_s(self, s: int) -> None:
        """Global shape is ``s × process_count``, which is only coherent
        when every process contributes the SAME shard count — topology
        does not guarantee that (5 shards over 2 hosts), and a mismatch
        would hang the next collective with no diagnostic. Unconditional
        (never cached): _place is itself collective under the lockstep
        contract, and a per-value cache would desynchronize the group the
        first time one process's S diverges (the cached side would skip
        the allgather the other side enters)."""
        from jax.experimental import multihost_utils

        counts = np.asarray(multihost_utils.process_allgather(np.int64(s)))
        if not (counts == s).all():
            raise ValueError(
                f"multi-host placement needs a uniform per-process shard "
                f"count; got {counts.tolist()} — pad every process to the "
                "same S (empty shards are all-zero rows)"
            )

    def _place(self, arr, lead_dims: int):
        s = arr.shape[lead_dims]
        w = arr.shape[-1]
        if self.multihost:
            n_proc = jax.process_count()
            self._check_uniform_s(s)
            s_global = s * n_proc
            spec = self._spec(s_global, w, lead_dims)
            if len(spec) <= lead_dims or spec[lead_dims] != AXIS_SHARDS:
                raise ValueError(
                    f"multi-host placement needs the shards axis sharded: "
                    f"global S={s_global} not divisible by mesh "
                    f"{self.mesh.shape[AXIS_SHARDS]} shard rows"
                )
            global_shape = (
                arr.shape[:lead_dims] + (s_global,) + arr.shape[lead_dims + 1 :]
            )
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, spec), arr, global_shape
            )
        return jax.device_put(arr, NamedSharding(self.mesh, self._spec(s, w, lead_dims)))

    def place_stack(self, stacked):
        """uint32[R, S, W] (or [D, S, W] BSI block) → sharded device array.
        Multi-host: S is this process's shard count; the global array
        concatenates every process's slice along S."""
        return self._place(stacked, 1)

    def place_rows(self, arr):
        """uint32[S, W] → sharded device array."""
        return self._place(arr, 0)


class MeshQueryEngine:
    """Compiles and caches sharded query programs over a fixed mesh."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._aot: set[tuple] = set()

    def _call(self, name: str, prog, *args):
        """Explicit AOT compile per (program, shapes) before the first
        call — jit's lazy compile-on-call path is pathologically slow on
        remote/tunneled accelerators and skips the persistent compile
        cache (see executor.compile.QueryCompiler.call_program, where
        this was measured: the subsequent concrete prog() call reuses
        the AOT-compiled executable rather than recompiling — measured
        ~0 s after a sub-second lower().compile() for a program whose
        lazy path took a minute). Static trailing args (e.g. top-k's k,
        a plain int or numpy scalar — NOT an ndarray) pass through to
        lower() as-is."""
        shapes = tuple(
            jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if isinstance(x, (np.ndarray, jax.Array))
            else x
            for x in args
        )
        sig = (name,) + tuple(
            (s.shape, s.dtype, s.sharding)
            if isinstance(s, jax.ShapeDtypeStruct)
            else s
            for s in shapes
        )
        if sig not in self._aot:
            prog.lower(*shapes).compile()
            self._aot.add(sig)
        return prog(*args)

    # ------------------------------------------------------------ placement
    def spec_matrix(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(None, AXIS_SHARDS, AXIS_WORDS))

    def spec_row(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(AXIS_SHARDS, AXIS_WORDS))

    def place_matrix(self, stacked: np.ndarray):
        """uint32[R, S, W] (row-major) → device, sharded (shards, words)."""
        return jax.device_put(stacked, self.spec_matrix())

    def place_row(self, stacked: np.ndarray):
        """uint32[S, W] → device."""
        return jax.device_put(stacked, self.spec_row())

    # ------------------------------------------------------------- programs
    def count_and(self, a, b):
        return self._call("count_and", self._count_and_prog, a, b)

    def topn(self, matrix, filt, k: int):
        return self._call("topn", self._topn_prog, matrix, filt, k)

    def bsi_sum(self, slices, filt):
        return self._call("bsi_sum", self._bsi_sum_prog, slices, filt)

    def ingest_and_aggregate(self, matrix, delta, filt):
        return self._call(
            "ingest_and_aggregate", self._ingest_prog, matrix, delta, filt
        )

    @functools.cached_property
    def _count_and_prog(self):
        @jax.jit
        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=P(),
        )
        def prog(a, b):
            local = ops.count_and(a, b)  # staged i32→i64 (see ops.popcount)
            return jax.lax.psum(jax.lax.psum(local, AXIS_WORDS), AXIS_SHARDS)

        return prog

    @functools.cached_property
    def _topn_prog(self):
        """(matrix [R,S,W], filt [S,W]) → per-row global counts int64[R]
        (psum over both axes; top_k happens on the replicated vector)."""

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=P(),
        )
        def counts_prog(matrix, filt):
            # [R, S_local] i32; i64 only past this point (layout: count_and)
            per = ops.popcount_rows(matrix & filt[None])
            local = jnp.sum(per.astype(jnp.int64), axis=1)
            return jax.lax.psum(jax.lax.psum(local, AXIS_WORDS), AXIS_SHARDS)

        @functools.partial(jax.jit, static_argnums=(2,))
        def prog(matrix, filt, k: int):
            counts = counts_prog(matrix, filt)
            k = min(k, counts.shape[0])
            vals, ids = jax.lax.top_k(counts, k)
            return vals, ids.astype(jnp.int32)

        return prog

    @functools.cached_property
    def _tanimoto_prog(self):
        """(matrix [R,S,W], query [S,W]) → (scores f32[k], ids i32[k]) —
        BASELINE config 5 (chemical-similarity search) as ONE SPMD
        program: per-device partial |a∩q| and |a| popcounts, psum over
        words-then-shards (the words hop rides the fast/ICI minor axis),
        Tanimoto on the replicated vectors, top_k replicated."""

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=(P(), P(), P()),
        )
        def counts_prog(matrix, query):
            inter = jnp.sum(
                ops.popcount_rows(matrix & query[None]).astype(jnp.int64),
                axis=1,
            )
            row_pop = jnp.sum(
                ops.popcount_rows(matrix).astype(jnp.int64), axis=1
            )
            q_pop = jnp.sum(ops.popcount_rows(query).astype(jnp.int64))
            red = lambda v: jax.lax.psum(
                jax.lax.psum(v, AXIS_WORDS), AXIS_SHARDS
            )
            return red(inter), red(row_pop), red(q_pop)

        @functools.partial(jax.jit, static_argnums=(2,))
        def prog(matrix, query, k: int):
            inter, row_pop, q_pop = counts_prog(matrix, query)
            inter = inter.astype(jnp.float32)
            union = row_pop.astype(jnp.float32) + q_pop.astype(jnp.float32) - inter
            scores = jnp.where(union > 0, inter / union, 0.0)
            k = min(k, scores.shape[0])
            vals, ids = jax.lax.top_k(scores, k)
            return vals, ids.astype(jnp.int32)

        return prog

    def tanimoto(self, matrix, query, k: int):
        return self._call("tanimoto", self._tanimoto_prog, matrix, query, k)

    @functools.cached_property
    def _bsi_sum_prog(self):
        """(slices [D,S,W], filt [S,W]) → (sum int64, count int64)."""

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(AXIS_SHARDS, AXIS_WORDS)),
            out_specs=(P(), P()),
        )
        def prog(slices, filt):
            exists = slices[bsi_ops.EXISTS_ROW]
            sign = slices[bsi_ops.SIGN_ROW]
            mag = slices[bsi_ops.OFFSET_ROW :]
            pos = (exists & ~sign & filt)[None]
            neg = (exists & sign & filt)[None]
            depth = mag.shape[0]
            weights = jnp.asarray([1 << k for k in range(depth)], dtype=jnp.int64)
            pc = jnp.sum(ops.popcount_rows(mag & pos).astype(jnp.int64), axis=1)
            nc = jnp.sum(ops.popcount_rows(mag & neg).astype(jnp.int64), axis=1)
            local_sum = jnp.sum((pc - nc) * weights)
            local_n = ops.popcount(exists & filt)
            total = jax.lax.psum(jax.lax.psum(local_sum, AXIS_WORDS), AXIS_SHARDS)
            n = jax.lax.psum(jax.lax.psum(local_n, AXIS_WORDS), AXIS_SHARDS)
            return total, n

        return prog

    @functools.cached_property
    def _ingest_prog(self):
        """The full "step": apply a packed write delta to the row matrix
        (device-side ingest, the donated-buffer mutation path) then compute
        the standing aggregates — one compiled program, zero host round
        trips (reference analogue: fragment.bulkImport + executor pass).

        (matrix [R,S,W], delta [R,S,W], filt [S,W])
            → (new_matrix, per-row counts int64[R], total int64)
        """

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(
                P(None, AXIS_SHARDS, AXIS_WORDS),
                P(None, AXIS_SHARDS, AXIS_WORDS),
                P(AXIS_SHARDS, AXIS_WORDS),
            ),
            out_specs=(P(None, AXIS_SHARDS, AXIS_WORDS), P(), P()),
        )
        def prog(matrix, delta, filt):
            new_matrix = matrix | delta
            local_counts = jnp.sum(
                ops.popcount_rows(new_matrix & filt[None]).astype(jnp.int64),
                axis=1,
            )
            counts = jax.lax.psum(
                jax.lax.psum(local_counts, AXIS_WORDS), AXIS_SHARDS
            )
            total = jnp.sum(counts)
            return new_matrix, counts, total

        return jax.jit(prog, donate_argnums=(0,))


def stack_field_matrices(field, shards: list[int]) -> np.ndarray:
    """Stack a field's standard-view fragment matrices → uint32[R, S, W]
    (host-side, row-major; rows padded to the max across shards)."""
    from pilosa_tpu.core import VIEW_STANDARD
    from pilosa_tpu.executor.compile import stack_view_matrices

    return stack_view_matrices(field.view(VIEW_STANDARD), shards)[0]
