"""Movement admission lane (docs/resize.md).

Every bulk data-movement path — rebalance pulls, anti-entropy handoff
pushes, restore adopts — moves whole fragments as serialized roaring
frames through the SAME admission lane, so movement can never starve
serving: transfers hold a bounded concurrency slot and pay a byte-rate
token bucket (``movement-max-concurrent`` / ``movement-max-mbit``)
before their bytes touch the wire, and every transfer is visible while
in flight (`GET /debug/cluster`) and accounted after
(`rebalance_bytes_total{direction}` / `fragments_moved_total` /
`movement_throttle_waits`, plus the ``movement`` row in
`GET /debug/resources`).

The lane deliberately owns NO transport: callers bring their own
resilient-client RPCs (the `resilience` analyzer rule pins movement to
that chain) and merely bracket them with :meth:`MovementLane.transfer`
+ :meth:`MovementLane.throttle`.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from collections import deque
from contextlib import contextmanager

from pilosa_tpu.utils import sanitize


def fragment_checksum(data: bytes) -> str:
    """Content hash of one serialized fragment frame. ``serialize``
    run-compacts containers on the way out, so identical logical
    content yields identical bytes — the digest is a convergence
    witness, not just a transfer integrity check."""
    return hashlib.blake2b(data, digest_size=16).hexdigest()


class MovementMeter:
    """Rolling movement-throughput accounting: lifetime totals tagged
    by direction (pull / push / restore) plus a sliding-window rate,
    read by the /debug/resources "movement" row. Window math is
    monotonic throughout (mirrors stats.IngestMeter)."""

    WINDOW_S = 60.0

    def __init__(self) -> None:
        self._lock = sanitize.make_lock("MovementMeter._lock")
        self.bytes_by_direction: dict[str, int] = {}
        self.fragments_total = 0
        self.throttle_waits = 0
        self._events: list[tuple[float, int]] = []

    def record(self, direction: str, nbytes: int) -> None:
        now = time.monotonic()
        with self._lock:
            self.bytes_by_direction[direction] = (
                self.bytes_by_direction.get(direction, 0) + nbytes
            )
            self.fragments_total += 1
            self._events.append((now, nbytes))
            self._trim(now)

    def note_throttle_wait(self) -> None:
        with self._lock:
            self.throttle_waits += 1

    def _trim(self, now: float) -> None:
        cut = now - self.WINDOW_S
        i = bisect.bisect_right(self._events, (cut, 1 << 62))
        if i:
            del self._events[:i]

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            if self._events:
                span = max(now - self._events[0][0], 1e-9)
                wb = sum(e[1] for e in self._events)
            else:
                span, wb = 0.0, 0
            return {
                "bytesByDirection": dict(self.bytes_by_direction),
                "bytesTotal": sum(self.bytes_by_direction.values()),
                "fragmentsTotal": self.fragments_total,
                "throttleWaits": self.throttle_waits,
                "windowSeconds": round(min(span, self.WINDOW_S), 3),
                "recentBytesPerS": round(wb / span, 1) if span else 0.0,
                "recentMbitPerS": (
                    round(wb * 8 / span / 1e6, 3) if span else 0.0
                ),
            }


class MovementLane:
    """Bounded admission for whole-fragment transfers.

    - ``max_concurrent`` transfers hold a slot at once; excess callers
      block (movement threads, never the serving loop).
    - ``max_mbit`` > 0 paces aggregate payload bytes with a token
      bucket (1 s of burst); :meth:`throttle` sleeps off any deficit
      BEFORE the caller ships/adopts the frame, so a resize drains at a
      configured ceiling instead of line rate.

    Per-transfer progress rows live here (in-flight dict + a bounded
    history deque) for `GET /debug/cluster`.
    """

    HISTORY = 64

    def __init__(
        self,
        max_concurrent: int = 4,
        max_mbit: float = 0.0,
        stats=None,
    ) -> None:
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_mbit = float(max_mbit)
        self.stats = stats
        self.meter = MovementMeter()
        self._sem = threading.BoundedSemaphore(self.max_concurrent)
        self._lock = sanitize.make_lock("MovementLane._lock")
        self._active: dict[int, dict] = {}
        self._done: deque[dict] = deque(maxlen=self.HISTORY)
        self._next_id = 0
        self._bytes_per_s = self.max_mbit * 1e6 / 8.0
        # 1 s of burst, floored so tiny test rates still admit one frame
        self._burst = max(self._bytes_per_s, 65536.0)
        self._allowance = self._burst
        self._last = time.monotonic()

    # ------------------------------------------------------------ admission
    @contextmanager
    def transfer(
        self,
        direction: str,
        index: str,
        field: str = "",
        view: str = "",
        shard: int = -1,
        peer: str = "",
    ):
        """Hold one movement slot for the duration of a transfer and
        publish its progress row. Yields the row dict — the caller
        stamps ``bytes`` on it once the payload size is known."""
        row = {
            "id": 0,
            "direction": direction,
            "index": index,
            "field": field,
            "view": view,
            "shard": shard,
            "peer": peer,
            "bytes": 0,
            "state": "queued",
            "startedMonotonicS": time.monotonic(),
        }
        queued = not self._sem.acquire(blocking=False)
        if queued:
            # slot wait is admission backpressure too — visible in the
            # same counter as rate sleeps
            self.meter.note_throttle_wait()
            if self.stats is not None:
                self.stats.count("movement_throttle_waits")
            self._sem.acquire()
        with self._lock:
            self._next_id += 1
            row["id"] = self._next_id
            row["state"] = "active"
            self._active[row["id"]] = row
        try:
            yield row
            row["state"] = "done"
        except BaseException:
            row["state"] = "failed"
            raise
        finally:
            self._sem.release()
            with self._lock:
                self._active.pop(row["id"], None)
                row["seconds"] = round(
                    time.monotonic() - row.pop("startedMonotonicS"), 3
                )
                self._done.append(row)

    def throttle(self, nbytes: int) -> float:
        """Pay ``nbytes`` into the token bucket; sleep off any deficit.
        Returns the seconds slept (0.0 when unthrottled)."""
        if self._bytes_per_s <= 0 or nbytes <= 0:
            return 0.0
        with self._lock:
            now = time.monotonic()
            self._allowance = min(
                self._burst,
                self._allowance + (now - self._last) * self._bytes_per_s,
            )
            self._last = now
            self._allowance -= nbytes
            deficit = -self._allowance
        if deficit <= 0:
            return 0.0
        wait = deficit / self._bytes_per_s
        self.meter.note_throttle_wait()
        if self.stats is not None:
            self.stats.count("movement_throttle_waits")
        time.sleep(wait)
        return wait

    # ----------------------------------------------------------- accounting
    def account(self, direction: str, nbytes: int) -> None:
        """Record one completed fragment transfer of ``nbytes``."""
        self.meter.record(direction, nbytes)
        if self.stats is not None:
            self.stats.count(
                "rebalance_bytes_total", nbytes, tags={"direction": direction}
            )
            self.stats.count("fragments_moved_total")

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        with self._lock:
            active = [dict(r) for r in self._active.values()]
            recent = [dict(r) for r in self._done]
        now = time.monotonic()
        for r in active:
            r["ageS"] = round(now - r.pop("startedMonotonicS"), 3)
        return {
            "maxConcurrent": self.max_concurrent,
            "maxMbit": self.max_mbit,
            "active": sorted(active, key=lambda r: r["id"]),
            "recent": recent,
            "meter": self.meter.snapshot(),
        }
