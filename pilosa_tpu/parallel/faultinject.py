"""Deterministic fault injection for the node→node data plane.

Every behavior in docs/fault-tolerance.md — retry-then-succeed, replica
failover, breaker trips, deadline exhaustion — is testable without real
network chaos: a ``FaultInjector`` holds seeded, rule-based faults and
``FaultInjectingClient`` (an ``InternalClient`` subclass) consults them
at the single transport chokepoint (``_request``) before any bytes move.
Faults therefore surface to callers exactly like real failures do (as
``PeerError`` with a status, or as added latency), underneath the retry
and breaker layers in ``parallel/resilience.py``.

Rules are JSON objects:

    {"peer": "127.0.0.1:9101",      # substring match on the peer URI ("" = all)
     "path": "/internal/query",     # prefix match on the request path ("" = all)
     "method": "POST",              # exact match ("" = all)
     "action": "http",              # drop | delay | http | blackhole
     "status": 503,                 # http action: injected status code
     "times": 2,                    # fire for the first N matches, then inert
                                    # (0/absent = every match; the
                                    #  "first-N-then-ok" shape)
     "delay_ms": 100, "jitter_ms": 50}   # delay action; also honored as a
                                         # pre-fault latency on drop/blackhole

Actions: ``drop`` raises a connection-reset-shaped transport error;
``delay`` sleeps ``delay_ms`` + U(0, jitter_ms) (seeded RNG) then lets
the request proceed; ``http`` fails with the given 5xx/4xx status;
``blackhole`` fails EVERY match until the rule set is cleared (the
unreachable-peer shape — ``times`` is ignored).

Configured three ways: the ``fault-rules`` config key (JSON list) /
``PILOSA_TPU_FAULT_RULES`` env var, seeded by ``fault-seed``; or at
runtime through the debug route (GET/POST/DELETE ``/debug/faults``) so
an operator can rehearse a failure on a live cluster and clear it
without a restart.
"""

from __future__ import annotations

import json
import random
import threading
import time

from pilosa_tpu.parallel.client import InternalClient, PeerError

_ACTIONS = ("drop", "delay", "http", "blackhole")


class FaultRule:
    __slots__ = (
        "peer", "path", "method", "action", "status", "times",
        "delay_ms", "jitter_ms", "fires",
    )

    def __init__(self, spec: dict):
        self.peer = spec.get("peer", "")
        self.path = spec.get("path", "")
        self.method = spec.get("method", "")
        self.action = spec.get("action", "drop")
        if self.action not in _ACTIONS:
            raise ValueError(
                f"fault action must be one of {_ACTIONS}, got {self.action!r}"
            )
        self.status = int(spec.get("status", 503))
        self.times = int(spec.get("times", 0))
        self.delay_ms = float(spec.get("delay_ms", 0.0))
        self.jitter_ms = float(spec.get("jitter_ms", 0.0))
        self.fires = 0

    def matches(self, method: str, uri: str, path: str) -> bool:
        if self.method and self.method != method:
            return False
        if self.peer and self.peer not in uri:
            return False
        if self.path and not path.startswith(self.path):
            return False
        # blackhole ignores `times`: it fails until cleared
        if self.action != "blackhole" and self.times > 0 and self.fires >= self.times:
            return False
        return True

    def to_json(self) -> dict:
        return {
            "peer": self.peer,
            "path": self.path,
            "method": self.method,
            "action": self.action,
            "status": self.status,
            "times": self.times,
            "delay_ms": self.delay_ms,
            "jitter_ms": self.jitter_ms,
            "fires": self.fires,
        }


class FaultInjector:
    """Seeded rule set shared by one node's outgoing client chain and
    its /debug/faults route.  Thread-safe; the no-rules fast path is one
    attribute read, so an always-installed injector costs nothing in
    production."""

    def __init__(self, rules: list[dict] | None = None, seed: int = 0,
                 sleep=time.sleep):
        self._lock = threading.Lock()
        self._rules: list[FaultRule] = []
        self._rng = random.Random(seed)
        self.seed = seed
        self._sleep = sleep
        # concurrency high-water mark across delayed requests — the
        # heartbeat-fan-out test reads this to prove probes overlap
        self._active = 0
        self.max_concurrent = 0
        if rules:
            self.set_rules(rules, seed)

    @classmethod
    def from_config(cls, config) -> "FaultInjector":
        rules: list[dict] = []
        raw = getattr(config, "fault_rules", "") or ""
        if raw:
            parsed = json.loads(raw)
            if not isinstance(parsed, list):
                raise ValueError("fault-rules must be a JSON list of rules")
            rules = parsed
        return cls(rules, seed=getattr(config, "fault_seed", 0))

    @property
    def armed(self) -> bool:
        return bool(self._rules)

    def set_rules(self, rules: list[dict], seed: int | None = None) -> None:
        parsed = [FaultRule(r) for r in rules]
        with self._lock:
            if seed is not None:
                self.seed = seed
                self._rng = random.Random(seed)
            self._rules = parsed

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [r.to_json() for r in self._rules],
                "maxConcurrent": self.max_concurrent,
            }

    def before_request(self, method: str, uri: str, path: str) -> None:
        """Apply the first matching rule (rule order is the tiebreak —
        deterministic given the same arrival order).  Raises PeerError
        for failure actions; returns after sleeping for delay actions."""
        if not self._rules:
            return
        with self._lock:
            rule = next(
                (r for r in self._rules if r.matches(method, uri, path)), None
            )
            if rule is None:
                return
            rule.fires += 1
            delay_s = 0.0
            if rule.delay_ms > 0 or rule.jitter_ms > 0:
                delay_s = (
                    rule.delay_ms + self._rng.uniform(0.0, rule.jitter_ms)
                ) / 1e3
            action, status = rule.action, rule.status
            if delay_s > 0:
                self._active += 1
                self.max_concurrent = max(self.max_concurrent, self._active)
        try:
            if delay_s > 0:
                self._sleep(delay_s)
        finally:
            if delay_s > 0:
                with self._lock:
                    self._active -= 1
        if action == "delay":
            return
        if action == "http":
            raise PeerError(
                uri, f"HTTP {status}: injected fault", status=status
            )
        if action == "blackhole":
            raise PeerError(uri, "injected blackhole: peer unreachable")
        raise PeerError(uri, "injected connection drop: connection reset")


class FaultInjectingClient(InternalClient):
    """InternalClient with the injector consulted at the transport
    chokepoint.  ``injector=None`` behaves exactly like the base class
    (and is what standalone/non-cluster servers get)."""

    def __init__(self, timeout: float = 30.0, skip_verify: bool = False,
                 injector: FaultInjector | None = None):
        super().__init__(timeout=timeout, skip_verify=skip_verify)
        self.injector = injector

    def _request(self, method, uri, path, body=None, timeout=None,
                 content_type="application/json"):
        inj = self.injector
        if inj is not None:
            inj.before_request(method, uri, path)
        return super()._request(
            method, uri, path, body, timeout=timeout, content_type=content_type
        )
