"""Deterministic fault injection for the node→node data plane.

Every behavior in docs/fault-tolerance.md — retry-then-succeed, replica
failover, breaker trips, deadline exhaustion — is testable without real
network chaos: a ``FaultInjector`` holds seeded, rule-based faults and
``FaultInjectingClient`` (an ``InternalClient`` subclass) consults them
at the single transport chokepoint (``_request``) before any bytes move.
Faults therefore surface to callers exactly like real failures do (as
``PeerError`` with a status, or as added latency), underneath the retry
and breaker layers in ``parallel/resilience.py``.

Rules are JSON objects:

    {"peer": "127.0.0.1:9101",      # substring match on the peer URI ("" = all)
     "path": "/internal/query",     # prefix match on the request path ("" = all)
     "method": "POST",              # exact match ("" = all)
     "action": "http",              # drop | delay | http | blackhole
     "status": 503,                 # http action: injected status code
     "times": 2,                    # fire for the first N matches, then inert
                                    # (0/absent = every match; the
                                    #  "first-N-then-ok" shape)
     "delay_ms": 100, "jitter_ms": 50}   # delay action; also honored as a
                                         # pre-fault latency on drop/blackhole

Actions: ``drop`` raises a connection-reset-shaped transport error;
``delay`` sleeps ``delay_ms`` + U(0, jitter_ms) (seeded RNG) then lets
the request proceed; ``http`` fails with the given 5xx/4xx status;
``blackhole`` fails EVERY match until the rule set is cleared (the
unreachable-peer shape — ``times`` is ignored).

Configured three ways: the ``fault-rules`` config key (JSON list) /
``PILOSA_TPU_FAULT_RULES`` env var, seeded by ``fault-seed``; or at
runtime through the debug route (GET/POST/DELETE ``/debug/faults``) so
an operator can rehearse a failure on a live cluster and clear it
without a restart.
"""

from __future__ import annotations

import errno
import json
import os
import random
import signal
import threading
import time

from pilosa_tpu.parallel.client import InternalClient, PeerError

_ACTIONS = ("drop", "delay", "http", "blackhole")


class FaultRule:
    __slots__ = (
        "peer", "path", "method", "action", "status", "times",
        "delay_ms", "jitter_ms", "fires",
    )

    def __init__(self, spec: dict):
        self.peer = spec.get("peer", "")
        self.path = spec.get("path", "")
        self.method = spec.get("method", "")
        self.action = spec.get("action", "drop")
        if self.action not in _ACTIONS:
            raise ValueError(
                f"fault action must be one of {_ACTIONS}, got {self.action!r}"
            )
        self.status = int(spec.get("status", 503))
        self.times = int(spec.get("times", 0))
        self.delay_ms = float(spec.get("delay_ms", 0.0))
        self.jitter_ms = float(spec.get("jitter_ms", 0.0))
        self.fires = 0

    def matches(self, method: str, uri: str, path: str) -> bool:
        if self.method and self.method != method:
            return False
        if self.peer and self.peer not in uri:
            return False
        if self.path and not path.startswith(self.path):
            return False
        # blackhole ignores `times`: it fails until cleared
        if self.action != "blackhole" and self.times > 0 and self.fires >= self.times:
            return False
        return True

    def to_json(self) -> dict:
        return {
            "peer": self.peer,
            "path": self.path,
            "method": self.method,
            "action": self.action,
            "status": self.status,
            "times": self.times,
            "delay_ms": self.delay_ms,
            "jitter_ms": self.jitter_ms,
            "fires": self.fires,
        }


class FaultInjector:
    """Seeded rule set shared by one node's outgoing client chain and
    its /debug/faults route.  Thread-safe; the no-rules fast path is one
    attribute read, so an always-installed injector costs nothing in
    production."""

    def __init__(self, rules: list[dict] | None = None, seed: int = 0,
                 sleep=time.sleep):
        self._lock = threading.Lock()
        self._rules: list[FaultRule] = []
        self._rng = random.Random(seed)
        self.seed = seed
        self._sleep = sleep
        # concurrency high-water mark across delayed requests — the
        # heartbeat-fan-out test reads this to prove probes overlap
        self._active = 0
        self.max_concurrent = 0
        if rules:
            self.set_rules(rules, seed)

    @classmethod
    def from_config(cls, config) -> "FaultInjector":
        rules: list[dict] = []
        raw = getattr(config, "fault_rules", "") or ""
        if raw:
            parsed = json.loads(raw)
            if not isinstance(parsed, list):
                raise ValueError("fault-rules must be a JSON list of rules")
            rules = parsed
        return cls(rules, seed=getattr(config, "fault_seed", 0))

    @property
    def armed(self) -> bool:
        return bool(self._rules)

    def set_rules(self, rules: list[dict], seed: int | None = None) -> None:
        parsed = [FaultRule(r) for r in rules]
        with self._lock:
            if seed is not None:
                self.seed = seed
                self._rng = random.Random(seed)
            self._rules = parsed

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [r.to_json() for r in self._rules],
                "maxConcurrent": self.max_concurrent,
            }

    def before_request(self, method: str, uri: str, path: str) -> None:
        """Apply the first matching rule (rule order is the tiebreak —
        deterministic given the same arrival order).  Raises PeerError
        for failure actions; returns after sleeping for delay actions."""
        if not self._rules:
            return
        with self._lock:
            rule = next(
                (r for r in self._rules if r.matches(method, uri, path)), None
            )
            if rule is None:
                return
            rule.fires += 1
            delay_s = 0.0
            if rule.delay_ms > 0 or rule.jitter_ms > 0:
                delay_s = (
                    rule.delay_ms + self._rng.uniform(0.0, rule.jitter_ms)
                ) / 1e3
            action, status = rule.action, rule.status
            if delay_s > 0:
                self._active += 1
                self.max_concurrent = max(self.max_concurrent, self._active)
        try:
            if delay_s > 0:
                self._sleep(delay_s)
        finally:
            if delay_s > 0:
                with self._lock:
                    self._active -= 1
        if action == "delay":
            return
        if action == "http":
            raise PeerError(
                uri, f"HTTP {status}: injected fault", status=status
            )
        if action == "blackhole":
            raise PeerError(uri, "injected blackhole: peer unreachable")
        raise PeerError(uri, "injected connection drop: connection reset")


# --------------------------------------------------------------- FS faults
#
# The durable write protocol (utils/durable.py) consults an installed
# hook before every filesystem primitive it performs.  FSFaultInjector is
# that hook: seeded, rule-armed disk faults — EIO, ENOSPC, torn
# (partial) writes, and process death at an exact protocol point — so
# the chaos suite reaches the write path exactly where real faults
# would (docs/durability.md crash matrix).
#
# Rules are JSON objects:
#
#     {"op": "snapshot-write",  # durable.py hook op: wal-append |
#                               # snapshot-write | fsync | rename |
#                               # dirfsync | truncate ("" = all ops)
#      "path": "fragments/0",   # substring match on the file path ("" = all)
#      "action": "crash",       # eio | enospc | torn | crash | kill | delay
#      "after": 2,              # skip the first N matches (arm the fault at
#                               # a precise occurrence), then
#      "times": 1,              # fire for the next N matches (0 = forever)
#      "cap_bytes": 7,          # torn action: bytes actually written before
#                               # the cut (default: half the buffer)
#      "delay_ms": 50, "jitter_ms": 10}  # delay action (seeded jitter)
#
# Actions: ``eio``/``enospc`` raise the corresponding OSError (the disk
# said no; recovery must keep the old state authoritative); ``torn``
# caps the write at cap_bytes then dies — the kill-9-mid-write shape;
# ``crash`` raises durable.SimulatedCrash (in-process chaos: tears
# through recovery code like a process death, caught only by the test
# harness / compaction worker); ``kill`` SIGKILLs the process — the
# real thing, for the subprocess crash-recovery suite; ``delay`` sleeps
# (stretches a protocol window so a concurrent writer can be observed
# not blocking).
#
# Armed via config ``fs-fault-rules`` (JSON list) + the shared
# ``fault-seed``; Server.open installs the injector process-wide with
# ``durable.install_fs_hook``.

_FS_ACTIONS = ("eio", "enospc", "torn", "crash", "kill", "delay")


class FSFaultRule:
    __slots__ = (
        "op", "path", "action", "then", "after", "times", "cap_bytes",
        "delay_ms", "jitter_ms", "matched", "fires",
    )

    def __init__(self, spec: dict):
        self.op = spec.get("op", "")
        self.path = spec.get("path", "")
        self.action = spec.get("action", "eio")
        # torn rules: how the process dies after the capped write —
        # "crash" (SimulatedCrash, in-process suites) or "kill" (SIGKILL,
        # the subprocess crash-recovery suite)
        self.then = spec.get("then", "crash")
        if self.action not in _FS_ACTIONS:
            raise ValueError(
                f"fs fault action must be one of {_FS_ACTIONS}, "
                f"got {self.action!r}"
            )
        if self.then not in ("crash", "kill"):
            # a typo'd death mode would silently degrade SIGKILL to an
            # in-process SimulatedCrash — the operator's kill-9
            # rehearsal would exercise the weaker mode with no error
            raise ValueError(
                f"fs fault 'then' must be 'crash' or 'kill', "
                f"got {self.then!r}"
            )
        self.after = int(spec.get("after", 0))
        self.times = int(spec.get("times", 1))
        self.cap_bytes = int(spec.get("cap_bytes", -1))
        self.delay_ms = float(spec.get("delay_ms", 0.0))
        self.jitter_ms = float(spec.get("jitter_ms", 0.0))
        self.matched = 0  # occurrences seen (drives `after`)
        self.fires = 0

    def observe(self, op: str, path: str) -> bool:
        """Count a match and decide whether the fault WOULD fire on it —
        without consuming the firing (the injector consumes `fires` only
        on the one rule it selects). Deterministic: the `after`/`times`
        counters make the Nth occurrence of an op the crash point, every
        run — and every overlapping rule counts every occurrence, so an
        earlier rule firing can never skew a later rule's `after`."""
        if self.op and self.op != op:
            return False
        if self.path and self.path not in path:
            return False
        self.matched += 1
        if self.matched <= self.after:
            return False
        if self.times > 0 and self.fires >= self.times:
            return False
        return True

    def try_fire(self, op: str, path: str) -> bool:
        if self.observe(op, path):
            self.fires += 1
            return True
        return False

    def to_json(self) -> dict:
        return {
            "op": self.op,
            "path": self.path,
            "action": self.action,
            "then": self.then,
            "after": self.after,
            "times": self.times,
            "capBytes": self.cap_bytes,
            "delay_ms": self.delay_ms,
            "matched": self.matched,
            "fires": self.fires,
        }


class FSFaultInjector:
    """The ``durable.install_fs_hook`` protocol: ``check`` may raise or
    kill before a primitive touches the filesystem; ``write_cap`` caps a
    write's length for torn-write faults; ``torn`` performs the death
    that must follow a capped write.  Thread-safe; unarmed cost is one
    attribute read per primitive."""

    def __init__(self, rules: list[dict] | None = None, seed: int = 0,
                 sleep=time.sleep):
        self._lock = threading.Lock()
        self._rules: list[FSFaultRule] = []
        self._rng = random.Random(seed)
        self.seed = seed
        self._sleep = sleep
        # the rule whose capped write this thread just performed —
        # thread-local because write_cap() and the torn() death that
        # follows it happen on the SAME thread (durable._write), while
        # OTHER threads may be tearing through different rules
        # concurrently; one shared slot would fire the wrong `then`
        self._torn_local = threading.local()
        if rules:
            self.set_rules(rules, seed)

    @classmethod
    def from_config(cls, config) -> "FSFaultInjector":
        rules: list[dict] = []
        raw = getattr(config, "fs_fault_rules", "") or ""
        if raw:
            parsed = json.loads(raw)
            if not isinstance(parsed, list):
                raise ValueError("fs-fault-rules must be a JSON list of rules")
            rules = parsed
        return cls(rules, seed=getattr(config, "fault_seed", 0))

    @property
    def armed(self) -> bool:
        return bool(self._rules)

    def set_rules(self, rules: list[dict], seed: int | None = None) -> None:
        parsed = [FSFaultRule(r) for r in rules]
        with self._lock:
            if seed is not None:
                self.seed = seed
                self._rng = random.Random(seed)
            self._rules = parsed

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [r.to_json() for r in self._rules],
            }

    def _die(self, action: str, op: str, path: str) -> None:
        from pilosa_tpu.utils.durable import SimulatedCrash

        if action == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        raise SimulatedCrash(f"injected crash at {op} on {path}")

    # --------------------------------------------- durable.py hook protocol
    def check(self, op: str, path: str) -> None:
        if not self._rules:
            return
        with self._lock:
            # every non-torn rule observes (counts) the occurrence; only
            # the FIRST eligible rule fires — a firing rule must not
            # hide occurrences from the rules behind it
            rule = None
            for r in self._rules:
                if r.action == "torn":
                    continue  # torn rules count write_cap occurrences
                if r.observe(op, path) and rule is None:
                    r.fires += 1
                    rule = r
            if rule is None:
                return
            action = rule.action
            delay_s = (
                rule.delay_ms + self._rng.uniform(0.0, rule.jitter_ms)
            ) / 1e3 if action == "delay" else 0.0
        if action == "delay":
            self._sleep(delay_s)
            return
        if action == "eio":
            raise OSError(errno.EIO, f"injected EIO at {op}", path)
        if action == "enospc":
            raise OSError(
                errno.ENOSPC, f"injected ENOSPC at {op}", path
            )
        self._die(action, op, path)

    def write_cap(self, op: str, path: str, nbytes: int) -> int | None:
        if not self._rules:
            return None
        with self._lock:
            rule = None
            cap = None
            for r in self._rules:
                if r.action != "torn":
                    continue  # non-torn rules count check occurrences
                eligible = r.observe(op, path)
                if not eligible or rule is not None:
                    continue
                c = r.cap_bytes if r.cap_bytes >= 0 else nbytes // 2
                if c >= nbytes:
                    # this write is smaller than the cap — nothing would
                    # tear. Don't consume the firing: a burnt `fires`
                    # with no injected fault makes the chaos scenario
                    # silently vacuous; the rule stays armed for a write
                    # it can actually truncate.
                    continue
                r.fires += 1
                rule = r
                cap = c
            if rule is None:
                return None
            self._torn_local.rule = rule
            return cap

    def torn(self, op: str, path: str) -> None:
        """The death that follows a capped write (durable._write calls
        this right after flushing the partial buffer — the bytes ARE on
        the file, exactly like a kill mid-write leaves them)."""
        rule = getattr(self._torn_local, "rule", None)
        self._die(rule.then if rule is not None else "crash", op, path)


class FaultInjectingClient(InternalClient):
    """InternalClient with the injector consulted at the transport
    chokepoint.  ``injector=None`` behaves exactly like the base class
    (and is what standalone/non-cluster servers get)."""

    def __init__(self, timeout: float = 30.0, skip_verify: bool = False,
                 injector: FaultInjector | None = None):
        super().__init__(timeout=timeout, skip_verify=skip_verify)
        self.injector = injector

    def _request(self, method, uri, path, body=None, timeout=None,
                 content_type="application/json"):
        inj = self.injector
        if inj is not None:
            inj.before_request(method, uri, path)
        return super()._request(
            method, uri, path, body, timeout=timeout, content_type=content_type
        )
