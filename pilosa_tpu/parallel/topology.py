"""Cluster topology: hash partitioning and replica placement.

Reference: cluster.go (partition(index, shard) = fnv % 256, partitionNodes,
shardNodes, ReplicaN, Node, Topology). Shards hash to 256 partitions;
each partition maps to a primary node with ``ReplicaN - 1`` consecutive
followers in sorted-node order — identical placement math on every node, no
coordination needed to route.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

PARTITION_N = 256

# states (reference: cluster.go NORMAL/STARTING/RESIZING/DEGRADED)
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_RESIZING = "RESIZING"
STATE_DEGRADED = "DEGRADED"
STATE_REMOVED = "REMOVED"  # this node was removed from the cluster


class ShardUnavailableError(RuntimeError):
    """No alive owner can serve a shard (or this node left the cluster)."""


def _fnv1a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def partition(index: str, shard: int) -> int:
    """(index, shard) → partition id (reference: cluster.partition)."""
    return _fnv1a(index.encode() + struct.pack("<Q", shard)) % PARTITION_N


@dataclass
class Node:
    id: str
    uri: str
    is_coordinator: bool = False
    state: str = STATE_NORMAL
    alive: bool = True

    def to_json(self) -> dict:
        return {
            "id": self.id,
            "uri": self.uri,
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }


@dataclass
class Topology:
    nodes: list[Node] = field(default_factory=list)
    replica_n: int = 1
    # membership version: bumped by every applied add/remove. Heartbeat
    # reconciliation adopts the HIGHER-epoch list, so both growth and
    # shrink converge across nodes that missed a broadcast (reference:
    # memberlist incarnation numbers serving the same role).
    epoch: int = 0

    def __post_init__(self) -> None:
        self.nodes.sort(key=lambda n: n.id)

    def node(self, node_id: str) -> Node | None:
        for n in self.nodes:
            if n.id == node_id:
                return n
        return None

    def remove(self, node_id: str) -> bool:
        """Drop a node; shard ownership re-derives from the smaller node
        list (reference: cluster.go removeNode → ResizeJob placement diff)."""
        before = len(self.nodes)
        self.nodes = [n for n in self.nodes if n.id != node_id]
        if len(self.nodes) < before:
            self.epoch += 1
            return True
        return False

    def add(self, node: Node) -> bool:
        """Insert a joining node (idempotent by URI); shard ownership
        re-derives from the larger node list (reference: cluster.go
        memberlist join → ResizeJob placement diff)."""
        if any(n.uri == node.uri for n in self.nodes):
            return False
        # build-then-rebind, never sort in place: list.sort detaches the
        # buffer mid-sort, so a lock-free concurrent reader (read routing,
        # heartbeats) could observe an empty/partial node list during a
        # join (same discipline as remove/_adopt_topology)
        self.nodes = sorted([*self.nodes, node], key=lambda n: n.id)
        self.epoch += 1
        return True

    def partition_nodes(self, partition_id: int) -> list[Node]:
        """Replica chain for a partition: primary + next ReplicaN-1 nodes
        in sorted order (reference: cluster.partitionNodes)."""
        if not self.nodes:
            return []
        n = len(self.nodes)
        start = partition_id % n
        count = min(self.replica_n, n)
        return [self.nodes[(start + i) % n] for i in range(count)]

    def shard_nodes(self, index: str, shard: int) -> list[Node]:
        """Owner nodes of one shard (reference: cluster.shardNodes)."""
        return self.partition_nodes(partition(index, shard))

    def primary(self, index: str, shard: int) -> Node | None:
        """First alive owner — the node that executes reads for the shard."""
        for n in self.shard_nodes(index, shard):
            if n.alive:
                return n
        return None

    def owns(self, node_id: str, index: str, shard: int) -> bool:
        return any(n.id == node_id for n in self.shard_nodes(index, shard))
