"""Typed query-result wire codec shared by the internal client and the
cluster coordinator (reference: internal QueryResponse protobuf — here
JSON control with raw packed-word blobs via encoding/frame.py, base64
fallback for external/older callers)."""

from __future__ import annotations

from typing import Any

from pilosa_tpu.encoding import frame
from pilosa_tpu.executor import RowResult
from pilosa_tpu.parallel.client import decode_words_b64, encode_words_b64


def encode_result(r: Any, blobs: list[bytes] | None = None) -> dict:
    """Typed wire form of one query result. With ``blobs`` (framed
    internal transport — see encoding/frame.py), RowResult segments ride
    as raw packed-word binary referenced by blob index; without, they
    fall back to base64-in-JSON (kept for external/older callers)."""
    if isinstance(r, RowResult):
        if blobs is not None:
            segbin: dict[str, int] = {}
            for s, w in r.segments.items():
                segbin[str(s)] = len(blobs)
                blobs.append(frame.pack_u32(w))
            return {"type": "row", "segbin": segbin}
        return {
            "type": "row",
            "segments": {
                str(s): encode_words_b64(w) for s, w in r.segments.items()
            },
        }
    if isinstance(r, bool):
        return {"type": "bool", "value": r}
    if isinstance(r, int):
        return {"type": "count", "value": r}
    if isinstance(r, dict) and "value" in r and "count" in r:
        return {"type": "valCount", "value": r["value"], "count": r["count"]}
    if isinstance(r, dict) and "rows" in r:
        return {"type": "rowIDs", **r}
    if isinstance(r, list):
        if r and "group" in r[0]:
            return {"type": "groups", "groups": r}
        return {"type": "pairs", "pairs": r}
    if r is None:
        return {"type": "null"}
    raise TypeError(f"cannot encode result {r!r}")


def decode_result(d: dict, blobs: list | None = None) -> Any:
    t = d["type"]
    if t == "row":
        if "segbin" in d:
            return RowResult(
                {
                    int(s): frame.unpack_u32(blobs[i])
                    for s, i in d["segbin"].items()
                }
            )
        return RowResult({int(s): decode_words_b64(w) for s, w in d["segments"].items()})
    if t == "bool":
        return d["value"]
    if t == "count":
        return d["value"]
    if t == "valCount":
        return {"value": d["value"], "count": d["count"]}
    if t == "rowIDs":
        return {k: v for k, v in d.items() if k != "type"}
    if t == "groups":
        return d["groups"]
    if t == "pairs":
        return d["pairs"]
    if t == "null":
        return None
    raise TypeError(f"cannot decode result {d!r}")


