"""L3 cluster + device-mesh parallelism.

Reference: cluster.go, gossip/, broadcast.go, http/client.go. Two scales of
parallelism live here:

- ``topology`` / ``cluster`` / ``client``: host-level scale-out — hash
  partitioning, replica chains, HTTP scatter-gather, anti-entropy;
- ``mesh``: chip-level scale-out — jax.sharding.Mesh execution of whole
  query batches with psum reductions over ICI (replaces the reference's
  per-node goroutine hot loop AND its HTTP reduce for intra-pod shards);
- ``multihost``: jax.distributed process-group init + DCN/ICI-aware mesh
  construction (words axis pinned within a host's ICI domain).
"""

from pilosa_tpu.parallel.topology import (
    PARTITION_N,
    Node,
    Topology,
    partition,
)

__all__ = ["Node", "Topology", "partition", "PARTITION_N", "shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_rep: bool = True):
    """THE repo-wide ``shard_map`` entry — the one compat point between
    the pinned test env (jax 0.4.37, where only
    ``jax.experimental.shard_map`` exists) and newer jax (where the API
    graduated to ``jax.shard_map`` and ``check_rep`` was renamed
    ``check_vma``). Every mesh program imports it from here so no module
    carries its own try/except, and a future jax bump edits one site.

    Lazy jax import: ``pilosa_tpu.parallel`` is imported by topology-only
    consumers (config, the analyzer fixtures) that must not pay — or
    trigger — a jax import."""
    import jax

    graduated = getattr(jax, "shard_map", None)
    if graduated is not None:
        try:
            return graduated(
                f,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=check_rep,
            )
        except TypeError:  # jax 0.5-0.6: graduated API, still check_rep
            return graduated(
                f,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_rep=check_rep,
            )
    from jax.experimental.shard_map import shard_map as _experimental

    return _experimental(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_rep
    )
