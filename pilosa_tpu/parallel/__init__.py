"""L3 cluster + device-mesh parallelism.

Reference: cluster.go, gossip/, broadcast.go, http/client.go. Two scales of
parallelism live here:

- ``topology`` / ``cluster`` / ``client``: host-level scale-out — hash
  partitioning, replica chains, HTTP scatter-gather, anti-entropy;
- ``mesh``: chip-level scale-out — jax.sharding.Mesh execution of whole
  query batches with psum reductions over ICI (replaces the reference's
  per-node goroutine hot loop AND its HTTP reduce for intra-pod shards);
- ``multihost``: jax.distributed process-group init + DCN/ICI-aware mesh
  construction (words axis pinned within a host's ICI domain).
"""

from pilosa_tpu.parallel.topology import (
    PARTITION_N,
    Node,
    Topology,
    partition,
)

__all__ = ["Node", "Topology", "partition", "PARTITION_N"]
